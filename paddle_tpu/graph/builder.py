"""GraphExecutor — compiles a ModelConfig into pure JAX functions.

TPU-native replacement for the reference's GradientMachine/NeuralNetwork
executor family (ref: paddle/gserver/gradientmachines/GradientMachine.cpp:31-60
factory; NeuralNetwork.cpp:230-288 forward/backward loops;
RecurrentGradientMachine.cpp per-timestep frame unrolling).

Re-design: instead of per-layer virtual forward()/backward() calls over
mutable Arguments, the whole graph becomes ONE pure function
`forward(params, feed) -> (outputs, costs, state)` traced and compiled by XLA;
`jax.grad` of the summed costs replaces every hand-written backward.  The
reference's RecurrentGradientMachine — which clones a frame network per
timestep and wires memories between frames — becomes a `lax.scan` whose body
executes the sub-model's layers, with memories as the scan carry.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig, ModelConfig, SubModelConfig
from paddle_tpu.graph.context import ForwardContext, TRAIN
from paddle_tpu.graph.registry import get_layer_fn, register_layer
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.parameter.init import init_parameter

Array = jax.Array


# Agent layer types are placeholders fed by the executor, like the reference's
# AgentLayer/ScatterAgentLayer/GatherAgentLayer plumbing
# (ref: paddle/gserver/layers/AgentLayer.cpp).
@register_layer("agent", "sequence_agent", "scatter_agent", "sequence_scatter_agent",
                "gather_agent", "sequence_gather_agent")
def _agent_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    raise AssertionError(f"agent layer {cfg.name!r} must be fed by the executor")


@register_layer("get_output")
def _get_output_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Expose a sub-model out_link (ref: GetOutputLayer.cpp); by the time the
    root walk reaches it, the scan has published the linked output."""
    return ctx.get_input(cfg, 0)


class GraphExecutor:
    """Builds and runs the layer graph described by a ModelConfig."""

    def __init__(self, model: ModelConfig, mesh=None, compute_dtype: str = ""):
        self.model = model
        self.mesh = mesh  # enables parallel layer paths (ring attention)
        # '' = run in param dtype; 'bfloat16' casts float params + inputs for
        # MXU-speed matmuls while softmax/log/BN-stats/costs stay float32
        # (settings(compute_dtype=...) / --compute_dtype)
        self.compute_dtype = compute_dtype
        self.layer_map: dict[str, LayerConfig] = {l.name: l for l in model.layers}
        # layers belonging to a recurrent sub-model are executed by its scan
        # (layer_names holds only the INNERMOST group's layers, so _sub_of
        # maps each layer to the group whose step body runs it)
        self._sub_of: dict[str, SubModelConfig] = {}
        self._sub_by_name: dict[str, SubModelConfig] = {}
        for sm in model.sub_models:
            if sm.is_recurrent_layer_group:
                self._sub_by_name[sm.name] = sm
                for ln in sm.layer_names:
                    self._sub_of[ln] = sm
        # per-group execution plans (nested groups appear as ('scan', child)
        # items inside their parent's plan)
        self._sub_plan: dict[str, list[tuple[str, Any]]] = {}
        self._plan = self._build_plan()
        # per-group suffix-deferral splits (see _split_deferred), lazy
        self._defer_cache: dict[str, Optional[dict]] = {}

    # -- planning ---------------------------------------------------------
    def _build_plan(self) -> list[tuple[str, Any]]:
        """Execution plans: ('layer', cfg) and ('scan', sub_model) items in
        config order (the DSL emits layers topologically, like config_parser).
        The top-level plan holds root groups; each group's own plan
        (self._sub_plan) interleaves its layers with nested child scans."""
        plan: list[tuple[str, Any]] = []
        seen_subs: set[str] = set()
        for l in self.model.layers:
            sm = self._sub_of.get(l.name)
            if sm is None:
                if l.type != "data":
                    plan.append(("layer", l))
                continue
            self._sub_plan.setdefault(sm.name, []).append(("layer", l))
            # first appearance of a group (or of any of its descendants)
            # emits a ('scan', group) item into its parent's plan
            child = sm
            while child is not None and child.name not in seen_subs:
                seen_subs.add(child.name)
                if child.parent:
                    self._sub_plan.setdefault(child.parent, []).append(
                        ("scan", child))
                    child = self._sub_by_name[child.parent]
                else:
                    plan.append(("scan", child))
                    child = None
        return plan

    # -- parameters -------------------------------------------------------
    def init_params(self, rng: jax.Array) -> dict[str, Array]:
        params: dict[str, Array] = {}
        for i, pc in enumerate(self.model.parameters):
            params[pc.name] = init_parameter(pc, jax.random.fold_in(rng, i))
        return params

    def init_state(self) -> dict[str, Any]:
        """Mutable layer state (batch-norm moving stats) — built lazily on the
        first forward; an empty dict is a valid initial state."""
        return {}

    @property
    def static_param_names(self) -> set[str]:
        return {p.name for p in self.model.parameters if p.is_static}

    # -- forward ----------------------------------------------------------
    def prepare(self, params: dict[str, Array], feed: dict[str, Argument]):
        """Pre-forward transforms shared by every execution path (plain
        forward and the pipeline executor): stop_gradient on static
        parameters, and the mixed-precision cast of float params/inputs."""
        static = self.static_param_names
        if static:
            params = {k: (jax.lax.stop_gradient(v) if k in static else v)
                      for k, v in params.items()}
        if self.compute_dtype:
            dt = jnp.dtype(self.compute_dtype)
            params = {k: (v.astype(dt) if jnp.issubdtype(v.dtype, jnp.floating)
                          else v) for k, v in params.items()}
            def _cast(arg):
                if (arg.value is not None
                        and jnp.issubdtype(arg.value.dtype, jnp.floating)):
                    arg = arg.replace(value=arg.value.astype(dt))
                if arg.sparse_vals is not None:
                    arg = arg.replace(sparse_vals=arg.sparse_vals.astype(dt))
                return arg
            feed = {name: _cast(arg) for name, arg in feed.items()}
        return params, feed

    def forward(
        self,
        params: dict[str, Array],
        feed: dict[str, Argument],
        state: Optional[dict[str, Any]] = None,
        mode: str = TRAIN,
        rng: Optional[jax.Array] = None,
        probes: Optional[dict[str, Array]] = None,
    ) -> tuple[dict[str, Argument], dict[str, Array], dict[str, Any]]:
        """Run the graph. Returns (layer outputs, per-sample costs, new state).

        `probes` maps layer names to zero arrays added to those layers'
        outputs: grad of the loss w.r.t. a probe IS that layer's output
        gradient — how the gradient_printer evaluator observes what the
        reference reads from Layer::getOutputGrad() (ref: Evaluator.cpp
        GradientPrinter; hand-written backward buffers replaced by autodiff).
        """
        params, feed = self.prepare(params, feed)
        ctx = ForwardContext(
            model=self.model, params=params, mode=mode, rng=rng,
            state_in=state or {}, mesh=self.mesh,
        )
        for name, arg in feed.items():
            ctx.outputs[name] = arg
        for kind, item in self._plan:
            if kind == "layer":
                cfg: LayerConfig = item
                if any(inp.input_layer_name not in ctx.outputs for inp in cfg.inputs):
                    # depends on a generator group's output — only produced by
                    # generate(); skip in plain forward
                    continue
                out = get_layer_fn(cfg.type)(ctx, cfg)
                if probes and cfg.name in probes and out.value is not None:
                    out = out.replace(value=out.value + probes[cfg.name])
                ctx.outputs[cfg.name] = out
            else:
                sm: SubModelConfig = item
                if sm.generator is not None and not sm.in_links:
                    continue  # generation-only group: run via generate()
                self._run_scan(ctx, sm)
        return ctx.outputs, ctx.costs, ctx.state_out

    def loss(
        self,
        params: dict[str, Array],
        feed: dict[str, Argument],
        state: Optional[dict[str, Any]] = None,
        mode: str = TRAIN,
        rng: Optional[jax.Array] = None,
        probes: Optional[dict[str, Array]] = None,
    ) -> tuple[Array, tuple[dict[str, Argument], dict[str, Array], dict[str, Any]]]:
        """Mean summed cost over the batch (ref: Argument::sumCosts / the
        reference divides by batch size at the updater via batch_size scaling —
        here the loss is per-sample mean, and the optimizer LR semantics match)."""
        outputs, costs, new_state = self.forward(params, feed, state, mode, rng,
                                                 probes)
        assert costs, "model has no cost layers"
        from paddle_tpu.utils.dtypes import promote_compute
        total = None
        for c in costs.values():
            s = jnp.mean(promote_compute(c))
            total = s if total is None else total + s
        return total, (outputs, costs, new_state)

    def run_group_layers(self, sm: SubModelConfig, sub: ForwardContext,
                         skip: Optional[set] = None) -> None:
        """Execute one timestep of a sub-model's layers; agent/alias layers
        must already be fed into sub.outputs.  Nested child groups run as
        inner scans at their position in the plan.  `skip` holds layer
        names deferred to post-scan batched execution."""
        for kind, item in self._sub_plan.get(sm.name, []):
            if kind == "scan":
                self._run_scan(sub, item)
                continue
            cfg: LayerConfig = item
            if cfg.name in sub.outputs:      # agents already fed
                continue
            if skip and cfg.name in skip:
                continue
            sub.outputs[cfg.name] = get_layer_fn(cfg.type)(sub, cfg)

    # -- suffix-layer deferral --------------------------------------------
    _DEFER_PROJS = {"fc", "full_matrix", "trans_full_matrix", "table",
                    "identity", "dot_mul", "scaling"}

    def _split_deferred(self, sm: SubModelConfig) -> Optional[dict]:
        """Layers of a recurrent group OUTSIDE the carry-dependency closure
        need not run inside the sequential scan: they can execute ONCE on
        the stacked [B, T, ...] sequence afterwards, turning T small
        per-step matmuls into one large MXU-shaped one.  The classic case
        is an attention decoder's vocabulary softmax projection — the
        dominant matmul of the step, feeding only the cost, never the
        recurrence.

        Returns {deferred, cfgs, emit} or None when nothing defers.  Only
        batch-agnostic layer types (last-dim ops) are eligible; a deferred
        layer may read scan-internal values (emitted per step) or in_link
        aliases (reconstructed as full sequences) but not static links
        (their [B, D] shape would not broadcast against [B, T, D])."""
        plan = self._sub_plan.get(sm.name, [])
        if sm.generator is not None or any(k == "scan" for k, _ in plan):
            return None
        layer_cfgs = {item.name: item for k, item in plan if k == "layer"}
        alias = set(sm.in_link_layers)
        statics = set(sm.static_link_layers)
        agents = {m.layer_name for m in sm.memories}

        # carry closure: memory-linked layers + their transitive inputs
        needed: set = set()
        stack = [m.link_name for m in sm.memories]
        while stack:
            n = stack.pop()
            if n in needed or n not in layer_cfgs:
                continue
            needed.add(n)
            for inp in layer_cfgs[n].inputs:
                stack.append(inp.input_layer_name)

        def safe(cfg: LayerConfig) -> bool:
            if any(i.input_layer_name in statics for i in cfg.inputs):
                return False
            if cfg.type in ("fc", "addto"):
                return True
            if cfg.type == "mixed":
                return (all(i.proj is None or i.proj.type in self._DEFER_PROJS
                            for i in cfg.inputs)
                        and all(op.type == "dot_mul" for op in cfg.operators))
            return False

        deferred = {item.name for k, item in plan if k == "layer"
                    and item.name not in needed and item.name not in alias
                    and item.name not in agents and safe(item)}
        # fixpoint: an inside layer consuming a deferred output pulls the
        # producer back inside
        changed = True
        while changed:
            changed = False
            for k, item in plan:
                if k != "layer" or item.name in deferred:
                    continue
                for inp in item.inputs:
                    if inp.input_layer_name in deferred:
                        deferred.discard(inp.input_layer_name)
                        changed = True
        if not deferred:
            return None
        cfgs = [item for k, item in plan
                if k == "layer" and item.name in deferred]
        emit: set = set()
        for cfg in cfgs:
            for inp in cfg.inputs:
                n = inp.input_layer_name
                if n in deferred or n in alias:
                    continue
                if n in layer_cfgs or n in agents:
                    emit.add(n)
        return {"deferred": deferred, "cfgs": cfgs, "emit": emit}

    # -- recurrent sub-model as lax.scan ---------------------------------
    def _run_scan(self, ctx: ForwardContext, sm: SubModelConfig) -> None:
        """Execute a recurrent layer group over the time axis
        (ref: RecurrentGradientMachine.cpp:372-560 forward: reorders sequences,
        clones a frame net per timestep, wires memory_t <- frame_{t-1}).

        Here: in_links are sliced per step, memories are the scan carry,
        out_links are stacked; variable lengths freeze the carry and mask
        outputs — no sorting, no cloning, one compiled scan.
        """
        in_link_alias = dict(zip(sm.in_links, sm.in_link_layers))
        static_alias = dict(zip(sm.static_links, sm.static_link_layers))

        # outside sequence inputs: [B, T, D] -> time-major [T, B, D].
        # A nested (level-2) in_link [B, S, T, ...] + sub_lengths instead
        # iterates over the SUBSEQUENCE axis: each step feeds one whole
        # [B, T, ...] sequence with that subsequence's lengths
        # (ref: RecurrentGradientMachine.cpp:626-699 hierarchical forward)
        xs = {}
        lengths = None
        sub_lens_src = None          # [B, S] of the nested in_link(s)
        sparse_links: dict[str, int] = {}   # in_link -> sparse_dim
        T = None
        nest_levels = {ctx.outputs[o].sub_lengths is not None
                       for o in sm.in_links}
        assert len(nest_levels) <= 1, (
            f"recurrent group {sm.name!r} mixes nested (SubsequenceInput) and "
            f"flat sequence in_links — all in_links must share one nesting "
            f"level (the step counts differ)")
        for outer in sm.in_links:
            arg = ctx.outputs[outer]
            assert arg.is_sequence, f"in_link {outer!r} must be a sequence"
            seq = arg.data
            if arg.sparse_dim:
                # keep the sparse-row structure through per-step slicing
                # (values reversed in lockstep with the ids below)
                sparse_links[outer] = arg.sparse_dim
                spvals = arg.sparse_vals
                if sm.reversed and arg.sub_lengths is None:
                    from paddle_tpu.ops.sequence import seq_reverse
                    spvals = seq_reverse(spvals, arg.lengths)
                xs["__spvals__" + outer] = jnp.moveaxis(spvals, 1, 0)
            if arg.sub_lengths is not None:
                assert not sm.reversed, \
                    "reverse=True on a nested recurrent group is not supported"
                xs[outer] = jnp.moveaxis(seq, 1, 0)              # [S, B, T, ..]
                xs["__sublen__" + outer] = jnp.moveaxis(arg.sub_lengths, 1, 0)
                sub_lens_src = arg.sub_lengths
                lengths = arg.lengths if lengths is None else jnp.maximum(lengths, arg.lengths)
                T = seq.shape[1] if T is None else max(T, seq.shape[1])
                continue
            if sm.reversed:
                from paddle_tpu.ops.sequence import seq_reverse
                seq = seq_reverse(seq, arg.lengths)
            xs[outer] = jnp.moveaxis(seq, 1, 0)
            lengths = arg.lengths if lengths is None else jnp.maximum(lengths, arg.lengths)
            T = seq.shape[1] if T is None else max(T, seq.shape[1])

        assert T is not None, f"recurrent group {sm.name!r} has no in_links"
        B = lengths.shape[0]

        # initial memories (scan carry): boot layer output, const id, or zeros
        carry0: dict[str, Array] = {}
        for mem in sm.memories:
            if mem.boot_layer_name:
                boot = ctx.outputs[mem.boot_layer_name].data
            elif mem.boot_with_const_id is not None:
                boot = jnp.full((B,), mem.boot_with_const_id, jnp.int32)
            else:
                boot = jnp.zeros((B, mem.size), jnp.float32)
            carry0[mem.link_name] = boot

        mode, rng = ctx.mode, ctx.rng
        params = ctx.params
        model = self.model

        # suffix layers outside the carry closure run post-scan, batched
        # over all timesteps (computed once per group, cached)
        if sm.name not in self._defer_cache:
            self._defer_cache[sm.name] = self._split_deferred(sm)
        spec = self._defer_cache[sm.name]
        defer_active = spec is not None and sub_lens_src is None
        skip = spec["deferred"] if defer_active else None
        emit_names = (sorted((set(sm.output_layer_names) - spec["deferred"])
                             | spec["emit"])
                      if defer_active else list(sm.output_layer_names))

        out_is_seq: dict[str, bool] = {}   # filled once during scan tracing

        def step(carry, inp):
            t = inp["__t__"]
            sub = ForwardContext(model=model, params=params, mode=mode,
                                 rng=(jax.random.fold_in(rng, t) if rng is not None else None))
            # feed sliced in_links through their in-group alias layers,
            # preserving ids-vs-value payload kind (an integer id sequence
            # must stay an ids Argument so table projections index correctly);
            # a nested link's slice is itself a sequence with this
            # subsequence's lengths
            for outer, inner in in_link_alias.items():
                sl = inp[outer]
                sub_len = inp.get("__sublen__" + outer)
                if outer in sparse_links:
                    sub.outputs[inner] = Argument(
                        ids=sl, sparse_vals=inp["__spvals__" + outer],
                        sparse_dim=sparse_links[outer], lengths=sub_len)
                elif jnp.issubdtype(sl.dtype, jnp.integer):
                    sub.outputs[inner] = Argument(ids=sl, lengths=sub_len)
                else:
                    sub.outputs[inner] = Argument(value=sl, lengths=sub_len)
            # feed static links: same value every step (ref: StaticInput)
            for outer, inner in static_alias.items():
                sub.outputs[inner] = ctx.outputs[outer]
            # feed memories: the agent layer reads last step's linked output
            for mem in sm.memories:
                prev = carry[mem.link_name]
                sub.outputs[mem.layer_name] = (
                    Argument(ids=prev) if prev.dtype in (jnp.int32, jnp.int64)
                    else Argument(value=prev))
            self.run_group_layers(sm, sub, skip=skip)
            valid = (t < lengths)
            new_carry = {}
            for mem in sm.memories:
                out = sub.outputs[mem.link_name].data
                v = valid.reshape((B,) + (1,) * (out.ndim - 1))
                prev = carry[mem.link_name]
                # keep the carry dtype fixed across steps (a stray fp32 op in
                # the step body must not flip a bf16 memory to fp32 mid-scan)
                new_carry[mem.link_name] = jnp.where(v, out, prev).astype(prev.dtype)
            emitted = {}
            for name in emit_names:
                o = sub.outputs[name]
                out_is_seq[name] = o.lengths is not None
                emitted[name] = o.data
            return new_carry, emitted

        inp_seq = {"__t__": jnp.arange(T)}
        inp_seq.update(xs)
        # Training scans remat the step body: backward then recomputes the
        # step's internals (attention scores, gate pre-activations, ...)
        # from the small carry instead of storing them per timestep — the
        # scan is HBM-bandwidth-bound, so saved residual traffic buys more
        # than the recompute costs (+8% on the seq2seq bench).  Forward-only
        # runs (test/generation) have no residuals to save; remat there only
        # inhibits XLA fusion across the checkpoint boundary.
        body = jax.checkpoint(step) if mode == TRAIN else step
        _, stacked = jax.lax.scan(body, carry0, inp_seq)

        # publish out_links as [B, T, D] sequences; a nested group whose step
        # emitted per-subsequence sequences publishes [B, S, T, D] with the
        # in_link's subsequence structure
        deferred_names = spec["deferred"] if defer_active else set()
        for name in sm.output_layer_names:
            if name in deferred_names:
                continue  # produced by the deferred batched execution below
            seq = jnp.moveaxis(stacked[name], 0, 1)
            if sm.reversed:
                from paddle_tpu.ops.sequence import seq_reverse
                seq = seq_reverse(seq, lengths)
            if sub_lens_src is not None and out_is_seq.get(name):
                ctx.outputs[name] = Argument(value=seq, lengths=lengths,
                                             sub_lengths=sub_lens_src)
            else:
                ctx.outputs[name] = Argument(value=seq, lengths=lengths)

        if defer_active:
            # run the suffix layers ONCE over the stacked sequences: one
            # [B*T, D] matmul instead of T [B, D] ones inside the scan.
            # rng folded with a large per-group constant so deferred dropout
            # masks are independent of the root context's key sequence and
            # of other groups' (the scan body folds small t values)
            drng = None
            if rng is not None:
                gid = [s.name for s in model.sub_models].index(sm.name)
                drng = jax.random.fold_in(rng, 2**31 - 1 - gid)
            dctx = ForwardContext(model=model, params=params, mode=mode,
                                  rng=drng)
            for outer, inner in in_link_alias.items():
                full = jnp.moveaxis(xs[outer], 0, 1)   # scan orientation
                if outer in sparse_links:
                    dctx.outputs[inner] = Argument(
                        ids=full,
                        sparse_vals=jnp.moveaxis(xs["__spvals__" + outer], 0, 1),
                        sparse_dim=sparse_links[outer], lengths=lengths)
                elif jnp.issubdtype(full.dtype, jnp.integer):
                    dctx.outputs[inner] = Argument(ids=full, lengths=lengths)
                else:
                    dctx.outputs[inner] = Argument(value=full, lengths=lengths)
            for name in spec["emit"]:
                v = jnp.moveaxis(stacked[name], 0, 1)
                if jnp.issubdtype(v.dtype, jnp.integer):
                    dctx.outputs[name] = Argument(ids=v, lengths=lengths)
                else:
                    dctx.outputs[name] = Argument(value=v, lengths=lengths)
            for cfg in spec["cfgs"]:
                dctx.outputs[cfg.name] = get_layer_fn(cfg.type)(dctx, cfg)
            for name in sm.output_layer_names:
                if name not in deferred_names:
                    continue
                seq = dctx.outputs[name].data
                if sm.reversed:
                    from paddle_tpu.ops.sequence import seq_reverse
                    seq = seq_reverse(seq, lengths)
                ctx.outputs[name] = Argument(value=seq, lengths=lengths)
