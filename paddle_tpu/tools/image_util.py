"""Image preprocessing + train-time augmentation utilities
(ref: python/paddle/utils/{image_util,preprocess_img}.py and the CUDA
perturbation kernel cuda/src/hl_perturbation_util.cu — random crop /
flip / rotate augmentation done on the host).

All functions operate on numpy arrays in CHW float32 layout (the layout
the conv layers consume after flattening) and are pure — batch-level
augmentation composes with the native shard loader or any provider.
"""

from __future__ import annotations

import numpy as np


def to_chw(img: np.ndarray) -> np.ndarray:
    """HWC uint8/float -> CHW float32."""
    if img.ndim == 2:
        img = img[:, :, None]
    return np.ascontiguousarray(img.transpose(2, 0, 1), np.float32)


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    """CHW center crop."""
    _, h, w = img.shape
    top, left = (h - size) // 2, (w - size) // 2
    return img[:, top:top + size, left:left + size]


def random_crop(img: np.ndarray, size: int,
                rng: np.random.Generator) -> np.ndarray:
    _, h, w = img.shape
    top = int(rng.integers(0, h - size + 1))
    left = int(rng.integers(0, w - size + 1))
    return img[:, top:top + size, left:left + size]


def horizontal_flip(img: np.ndarray) -> np.ndarray:
    return img[:, :, ::-1]


def rotate_90k(img: np.ndarray, k: int) -> np.ndarray:
    """Rotate by k*90 degrees (the perturbation kernel's cheap rotation)."""
    return np.rot90(img, k, axes=(1, 2))


def normalize(img: np.ndarray, mean: np.ndarray | float = 0.0,
              scale: float = 1.0) -> np.ndarray:
    """(img - mean) * scale; mean may be a per-channel CHW mean image."""
    return (img - mean) * scale


def augment(img: np.ndarray, crop_size: int, rng: np.random.Generator,
            train: bool = True, mean: np.ndarray | float = 0.0,
            scale: float = 1.0, flip: bool = True) -> np.ndarray:
    """The standard train/test pipeline (ref: preprocess_img.py usage):
    train = random crop + random flip; test = center crop."""
    if train:
        out = random_crop(img, crop_size, rng)
        if flip and rng.random() < 0.5:
            out = horizontal_flip(out)
    else:
        out = center_crop(img, crop_size)
    return np.ascontiguousarray(normalize(out, mean, scale), np.float32)


def compute_mean_image(imgs, shape: tuple[int, int, int]) -> np.ndarray:
    """Mean CHW image over a sample iterable (ref: image_util meta file)."""
    acc = np.zeros(shape, np.float64)
    n = 0
    for img in imgs:
        acc += img
        n += 1
    assert n > 0, "no images"
    return (acc / n).astype(np.float32)
