"""Multi-host cluster launcher.

TPU-native analog of the reference's fabric/SSH cluster starter
(ref: paddle/scripts/cluster_train/paddle.py + conf.py: copies the
workspace to every node, then starts paddle_pserver2 fleets and
paddle_trainer processes with --trainer_id/--pservers wiring).

Re-design: there is no pserver fleet — every host runs the SAME trainer
command under jax.distributed, with process 0 as the coordinator
(parallel/mesh.py:init_distributed).  XLA's collectives ride ICI within a
slice and DCN across slices; the launcher only has to start N identical
processes with {coordinator_address, num_processes, process_id} and any
trainer flags passed through.

Usage:
  python -m paddle_tpu.tools.cluster_launch \\
      --hosts host0,host1,host2,host3 --port 8476 \\
      --workspace /path/on/hosts -- \\
      --config=demo/image_classification/vgg_16_cifar.py --num_passes=10

With --dry_run the ssh commands are printed instead of executed (also how
the unit tests exercise this hermetically).
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys


def build_commands(hosts: list[str], port: int, workspace: str,
                   trainer_args: list[str], python: str = "python",
                   local: bool = False) -> list[list[str]]:
    """One command per host; host 0 doubles as the jax.distributed
    coordinator (ref: conf.py HOSTS + --trainer_id assignment).  With
    local=True the commands run under a local shell instead of ssh — the
    single-machine multi-process form (ref: scripts/submit_local.sh.in)."""
    if not hosts:
        raise SystemExit("cluster_launch: no hosts given (--hosts host0,host1,...)")
    # local mode ignores the host NAMES (only the count matters), so the
    # rendezvous must be on this machine no matter what the user listed
    coordinator = f"localhost:{port}" if local else f"{hosts[0]}:{port}"
    cmds = []
    for pid, host in enumerate(hosts):
        # exec: the shell must BECOME the trainer, so in --local mode the
        # kill/terminate paths in main() signal the trainer itself, not an
        # sh wrapper (an orphaned trainer keeps the coordinator port
        # blocked); over ssh the -tt pty makes a dropped connection HUP
        # the remote trainer for the same reason
        inner = (
            f"cd {shlex.quote(workspace)} && "
            f"exec {python} -m paddle_tpu.trainer_main "
            f"--coordinator_address={coordinator} "
            f"--num_processes={len(hosts)} --process_id={pid} "
            + " ".join(shlex.quote(a) for a in trainer_args)
        )
        if local:
            cmds.append(["sh", "-c", inner])
        else:
            cmds.append(["ssh", "-tt", "-o", "StrictHostKeyChecking=no",
                         host, inner])
    return cmds


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="launch one trainer process per host under jax.distributed")
    ap.add_argument("--hosts", required=True,
                    help="comma-separated host list; first is coordinator")
    ap.add_argument("--port", type=int, default=8476,
                    help="coordinator port (ref: conf.py PADDLE_PORT)")
    ap.add_argument("--workspace", default=".",
                    help="working directory on every host")
    ap.add_argument("--python", default="python")
    ap.add_argument("--local", action="store_true",
                    help="run every process on THIS machine via a local "
                         "shell instead of ssh (submit_local analog)")
    ap.add_argument("--dry_run", action="store_true",
                    help="print the ssh commands without running them")
    ap.add_argument("--timeout", type=float, default=0,
                    help="kill the whole fleet (nonzero exit) after this "
                         "many seconds — a wedged jax.distributed "
                         "rendezvous otherwise blocks forever")
    args, trainer_args = ap.parse_known_args(argv)
    if trainer_args and trainer_args[0] == "--":
        trainer_args = trainer_args[1:]

    hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
    cmds = build_commands(hosts, args.port, args.workspace, trainer_args,
                          args.python, local=args.local)
    if args.dry_run:
        for c in cmds:
            print(" ".join(shlex.quote(p) for p in c))
        return 0

    # jax.distributed.initialize is a barrier over all processes: if one host
    # dies at startup the others would block forever, so kill the survivors
    # as soon as any process exits nonzero
    import time
    # stdin=DEVNULL: concurrent `ssh -tt` processes would otherwise fight
    # over the launcher's tty (raw mode + competing reads swallow Ctrl-C,
    # defeating the KeyboardInterrupt teardown below); the doubled -t still
    # allocates the remote pty that HUPs the workers on disconnect
    procs = [subprocess.Popen(c, stdin=subprocess.DEVNULL) for c in cmds]
    deadline = time.monotonic() + args.timeout if args.timeout > 0 else None
    rc = 0
    try:
        while procs:
            if deadline is not None and time.monotonic() > deadline:
                print(f"cluster_launch: --timeout={args.timeout}s expired; "
                      f"killing {len(procs)} processes", file=sys.stderr)
                for q in procs:
                    q.kill()
                for q in procs:
                    q.wait()
                return rc or 124
            for p in list(procs):
                code = p.poll()
                if code is None:
                    continue
                procs.remove(p)
                if code != 0 and rc == 0:
                    rc = code
                    print(f"process exited with {code}; terminating peers",
                          file=sys.stderr)
                    for q in procs:
                        q.terminate()
            time.sleep(0.5)
    except KeyboardInterrupt:
        for q in procs:
            q.terminate()
        raise
    return rc


if __name__ == "__main__":
    sys.exit(main())
