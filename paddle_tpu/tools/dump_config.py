"""Parse a config file and dump the built TrainerConfig
(ref: python/paddle/utils/dump_config.py — prints the protobuf text form;
here the canonical serialization is JSON).

CLI: python -m paddle_tpu.tools.dump_config CONFIG [CONFIG_ARGS]
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("config")
    p.add_argument("config_args", nargs="?", default="")
    p.add_argument("--model_only", action="store_true",
                   help="dump only the ModelConfig section")
    args = p.parse_args(argv)

    from paddle_tpu.config.parser import parse_config
    cfg = parse_config(args.config, args.config_args)
    if args.model_only:
        print(cfg.model_config.to_json(indent=2))
    else:
        print(cfg.to_json(indent=2))


if __name__ == "__main__":
    main()
