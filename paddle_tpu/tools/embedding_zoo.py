"""Pretrained-embedding utilities (ref: demo/model_zoo/embedding/
{extract_para.py, paraconvert.py}).

The reference ships two scripts around its binary parameter files: extract
the rows of a big pretrained embedding that match a user dictionary, and
convert parameter files binary<->text.  Here the parameter container is
this framework's checkpoint .npz / plain .npy, and the text form is the
word2vec-style "word v1 v2 ... vD" per line, so embeddings interchange
with the wider ecosystem.

CLI:
    python -m paddle_tpu.tools.embedding_zoo extract \
        --pre_model emb.npy --pre_dict big.dict \
        --usr_model out.npy --usr_dict small.dict
    python -m paddle_tpu.tools.embedding_zoo to_text \
        --model emb.npy --dict words.dict --output emb.txt
    python -m paddle_tpu.tools.embedding_zoo from_text \
        --input emb.txt --model out.npy --dict out.dict
"""

from __future__ import annotations

import argparse

import numpy as np


def _read_dict(path: str) -> list[str]:
    with open(path) as f:
        return [ln.rstrip("\n") for ln in f if ln.rstrip("\n")]


def extract_rows(pre_emb: np.ndarray, pre_words: list[str],
                 usr_words: list[str],
                 unk_token: str = "<unk>") -> np.ndarray:
    """Rows of `pre_emb` for `usr_words` (ref: extract_para.py
    get_row_index + extract_parameters_by_usrDict).  A user word missing
    from the pretrained dictionary falls back to the `<unk>` row when the
    pretrained dict has one, else to the pretrained mean vector."""
    index = {w: i for i, w in enumerate(pre_words)}
    assert len(pre_words) == pre_emb.shape[0], \
        f"dict has {len(pre_words)} words, embedding {pre_emb.shape[0]} rows"
    if unk_token in index:
        fallback = pre_emb[index[unk_token]]
    else:
        fallback = pre_emb.mean(axis=0)
    out = np.empty((len(usr_words), pre_emb.shape[1]), pre_emb.dtype)
    misses = 0
    for r, w in enumerate(usr_words):
        i = index.get(w)
        if i is None:
            out[r] = fallback
            misses += 1
        else:
            out[r] = pre_emb[i]
    if misses:
        print(f"{misses}/{len(usr_words)} user words not in the pretrained "
              f"dictionary (filled with "
              f"{'<unk> row' if unk_token in index else 'mean vector'})")
    return out


def to_text(emb: np.ndarray, words: list[str], path: str) -> None:
    """word2vec-style text (ref: paraconvert.py --b2t; the first line
    carries the shape header like the reference's count:dim line)."""
    assert len(words) == emb.shape[0]
    with open(path, "w") as f:
        f.write(f"{emb.shape[0]} {emb.shape[1]}\n")
        for w, row in zip(words, emb):
            f.write(w + " " + " ".join(f"{v:.6g}" for v in row) + "\n")


def from_text(path: str) -> tuple[np.ndarray, list[str]]:
    """(ref: paraconvert.py --t2b)."""
    with open(path) as f:
        n, d = (int(t) for t in f.readline().split())
        words, rows = [], []
        for ln in f:
            parts = ln.split()   # tolerate double spaces / trailing blanks
            if not parts:
                continue
            words.append(parts[0])
            rows.append(np.asarray(parts[1:], np.float32))
    emb = np.stack(rows)
    assert emb.shape == (n, d), f"header {(n, d)} vs data {emb.shape}"
    return emb, words


def _load_emb(path: str, key: str = "") -> np.ndarray:
    if path.endswith(".npz"):
        data = np.load(path)
        if key:
            assert key in data.files, \
                f"--key {key!r} not in archive; available: {sorted(data.files)}"
            return np.asarray(data[key], np.float32)
        names = [k for k in data.files if "embedding" in k]
        if len(names) != 1:
            raise SystemExit(
                f"cannot identify the embedding array in {path} "
                f"(matches: {names or 'none'}); pass --key, available keys: "
                f"{sorted(data.files)}")
        return np.asarray(data[names[0]], np.float32)
    return np.asarray(np.load(path), np.float32)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    e = sub.add_parser("extract")
    e.add_argument("--pre_model", required=True)
    e.add_argument("--pre_dict", required=True)
    e.add_argument("--usr_model", required=True)
    e.add_argument("--usr_dict", required=True)
    e.add_argument("--key", default="", help=".npz array name if ambiguous")

    t = sub.add_parser("to_text")
    t.add_argument("--model", required=True)
    t.add_argument("--dict", dest="dict_path", required=True)
    t.add_argument("--output", required=True)
    t.add_argument("--key", default="", help=".npz array name if ambiguous")

    ft = sub.add_parser("from_text")
    ft.add_argument("--input", required=True)
    ft.add_argument("--model", required=True)
    ft.add_argument("--dict", dest="dict_path", required=True)

    args = p.parse_args(argv)
    if args.cmd == "extract":
        emb = extract_rows(_load_emb(args.pre_model, args.key),
                           _read_dict(args.pre_dict),
                           _read_dict(args.usr_dict))
        np.save(args.usr_model, emb)
        print(f"wrote {args.usr_model}: {emb.shape}")
    elif args.cmd == "to_text":
        to_text(_load_emb(args.model, args.key),
                _read_dict(args.dict_path), args.output)
        print(f"wrote {args.output}")
    else:
        emb, words = from_text(args.input)
        np.save(args.model, emb)
        with open(args.dict_path, "w") as f:
            f.write("\n".join(words) + "\n")
        print(f"wrote {args.model}: {emb.shape} and {args.dict_path}")


if __name__ == "__main__":
    main()
