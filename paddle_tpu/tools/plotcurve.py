"""Plot cost curves from trainer logs
(ref: python/paddle/utils/plotcurve.py — reads 'cost=' lines from
paddle_trainer output).

Parses lines like
  I 2026-... paddle_tpu.trainer] pass 3 batch 200: cost 0.1234 ...
or any line containing 'cost <float>' / 'cost=<float>'.  Writes a PNG when
matplotlib is importable, else renders an ASCII chart.

CLI: python -m paddle_tpu.tools.plotcurve LOGFILE [OUT.png]
     cat train.log | python -m paddle_tpu.tools.plotcurve - out.png
"""

from __future__ import annotations

import argparse
import re
import sys

_PAT = re.compile(r"cost[ =]([0-9.eE+-]+)")


def parse_costs(lines) -> list[float]:
    out = []
    for ln in lines:
        m = _PAT.search(ln)
        if m:
            try:
                out.append(float(m.group(1)))
            except ValueError:
                pass
    return out


def ascii_plot(ys: list[float], width: int = 72, height: int = 16) -> str:
    if not ys:
        return "(no cost lines found)"
    lo, hi = min(ys), max(ys)
    span = (hi - lo) or 1.0
    # downsample to width columns
    cols = []
    n = len(ys)
    for c in range(min(width, n)):
        seg = ys[c * n // min(width, n):(c + 1) * n // min(width, n)] or [ys[-1]]
        cols.append(sum(seg) / len(seg))
    grid = [[" "] * len(cols) for _ in range(height)]
    for c, v in enumerate(cols):
        r = int((hi - v) / span * (height - 1))
        grid[r][c] = "*"
    lines = [f"{hi:10.4f} +" + "".join(grid[0])]
    lines += ["           |" + "".join(row) for row in grid[1:-1]]
    lines.append(f"{lo:10.4f} +" + "".join(grid[-1]))
    lines.append(f"           {len(ys)} points, final {ys[-1]:.4f}")
    return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logfile")
    p.add_argument("output", nargs="?", default=None)
    args = p.parse_args(argv)

    src = sys.stdin if args.logfile == "-" else open(args.logfile)
    ys = parse_costs(src)
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        plt.figure(figsize=(8, 5))
        plt.plot(ys)
        plt.xlabel("log period")
        plt.ylabel("cost")
        plt.grid(True, alpha=0.3)
        out = args.output or "cost_curve.png"
        plt.savefig(out, dpi=120)
        print(f"wrote {out}")
    except ImportError:
        print(ascii_plot(ys))


if __name__ == "__main__":
    main()
