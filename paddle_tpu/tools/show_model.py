"""Inspect a checkpoint / merged bundle: config summary + parameter table
(ref: python/paddle/utils/show_pb.py — prints a serialized proto).

CLI: python -m paddle_tpu.tools.show_model PATH
  PATH: a pass-%05d dir, a model.npz, or a merged bundle from merge_model.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def show(path: str) -> None:
    from paddle_tpu.config.schema import TrainerConfig
    from paddle_tpu.tools.merge_model import load_bundle
    from paddle_tpu.trainer import checkpoint as ckpt

    cfg = None
    if os.path.isfile(path) and not path.endswith("model.npz"):
        cfg, params = load_bundle(path)
    else:
        data = ckpt.load_checkpoint(path)
        params = data["params"]
        if data.get("config_json"):
            cfg = TrainerConfig.from_json(data["config_json"])

    if cfg is not None and cfg.model_config is not None:
        mc = cfg.model_config
        print(f"model: {len(mc.layers)} layers, {len(mc.parameters)} parameters,"
              f" {len(mc.sub_models)} sub-models")
        for lc in mc.layers:
            acts = f" act={lc.active_type}" if lc.active_type else ""
            ins = ",".join(i.input_layer_name for i in lc.inputs)
            print(f"  layer {lc.name:<32} {lc.type:<18} size={lc.size}{acts}"
                  f"{'  <- ' + ins if ins else ''}")
    total = 0
    print("parameters:")
    for name in sorted(params):
        arr = np.asarray(params[name])
        total += arr.size
        print(f"  {name:<40} {str(arr.shape):<16} {arr.dtype}  "
              f"|mean|={np.abs(arr).mean():.5f}")
    print(f"total parameters: {total:,}")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path")
    args = p.parse_args(argv)
    show(args.path)


if __name__ == "__main__":
    main()
