"""Emit a Graphviz dot diagram of a model config
(ref: python/paddle/utils/make_model_diagram.py).

CLI: python -m paddle_tpu.tools.make_model_diagram CONFIG [OUT.dot] [CONFIG_ARGS]
"""

from __future__ import annotations

import argparse


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def model_to_dot(model) -> str:
    """ModelConfig -> dot source; sub-model (recurrent group) layers are
    clustered (the reference draws sub-graphs per submodel)."""
    lines = ["digraph model {", "  rankdir=BT;",
             '  node [shape=box, fontsize=10];']
    in_group: set[str] = set()
    for i, sm in enumerate(model.sub_models):
        lines.append(f"  subgraph cluster_{i} {{")
        lines.append(f'    label="{_esc(sm.name)}"; style=dashed;')
        for name in sm.layer_names:
            cfg = model.layer(name)
            label = f"{cfg.name}\\n{cfg.type} [{cfg.size}]"
            lines.append(f'    "{_esc(cfg.name)}" [label="{_esc(label)}"];')
            in_group.add(name)
        lines.append("  }")
    for cfg in model.layers:
        if cfg.name not in in_group:
            label = f"{cfg.name}\\n{cfg.type} [{cfg.size}]"
            shape = ", shape=ellipse" if cfg.type == "data" else ""
            lines.append(f'  "{_esc(cfg.name)}" [label="{_esc(label)}"{shape}];')
    for cfg in model.layers:
        for inp in cfg.inputs:
            attrs = ""
            if inp.input_parameter_name:
                attrs = f' [label="{_esc(inp.input_parameter_name)}", fontsize=8]'
            lines.append(f'  "{_esc(inp.input_layer_name)}" -> '
                         f'"{_esc(cfg.name)}"{attrs};')
    lines.append("}")
    return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("config")
    p.add_argument("output", nargs="?", default=None)
    p.add_argument("config_args", nargs="?", default="")
    args = p.parse_args(argv)

    from paddle_tpu.config.parser import parse_config
    cfg = parse_config(args.config, args.config_args)
    dot = model_to_dot(cfg.model_config)
    if args.output:
        with open(args.output, "w") as f:
            f.write(dot)
        print(f"wrote {args.output}")
    else:
        print(dot)


if __name__ == "__main__":
    main()
