"""Deploy/inspection/conversion tools (ref: paddle_merge_model,
python/paddle/utils/{dump_config,show_pb,make_model_diagram,plotcurve,
image_util,preprocess_img,torch2paddle}.py)."""
