"""Import torch model weights into a paddle_tpu checkpoint
(ref: python/paddle/utils/torch2paddle.py — converts legacy Torch7 nn
model binaries into paddle parameter files; here: a torch state_dict /
.pt file into a pass-%05d checkpoint loadable by Trainer/GradientMachine).

Matching strategy: explicit name_map wins, else parameters are paired by
shape in declaration order (torch Linear weights are [out, in] and are
transposed to this framework's [in, out] layout).

CLI: python -m paddle_tpu.tools.torch2paddle --config conf.py \\
         --torch model.pt --output ckpt_dir
"""

from __future__ import annotations

import argparse
from typing import Optional

import numpy as np


def convert_state_dict(state_dict, model_config,
                       name_map: Optional[dict[str, str]] = None,
                       transpose_linear: bool = True,
                       conv_transpose_keys=()) -> dict[str, np.ndarray]:
    """torch state_dict -> {paddle_tpu param name: np.ndarray}.

    `conv_transpose_keys`: state_dict keys holding nn.ConvTranspose2d
    weights, whose torch layout is [in, out/g, kH, kW] — the OPPOSITE
    first-two-axis order of a regular Conv2d.  They must be named
    explicitly because the array alone cannot reveal which layout it is
    (a square in==out transposed kernel would otherwise be silently
    scrambled by the [O, I, kh, kw] reshape rule).  Pass a tuple/list of
    keys for groups=1 layers, or a {key: groups} dict for grouped ones."""
    import jax

    from paddle_tpu.graph.builder import GraphExecutor

    ex = GraphExecutor(model_config)
    template = ex.init_params(jax.random.PRNGKey(0))
    shapes = {k: tuple(v.shape) for k, v in template.items()}

    torch_items = []
    for k, v in state_dict.items():
        # np.array(copy=True): tensor.numpy() ALIASES torch's live storage,
        # and jax's CPU backend can zero-copy numpy buffers — without the
        # copy, later in-place torch updates would mutate the "converted"
        # parameters
        arr = np.array(v.detach().cpu().numpy() if hasattr(v, "detach") else v,
                       dtype=np.float32)
        if k in conv_transpose_keys:
            assert arr.ndim == 4, f"{k} is not a 4-D conv kernel"
            g = (conv_transpose_keys[k]
                 if isinstance(conv_transpose_keys, dict) else 1)
            i, og, kh, kw = arr.shape
            assert i % g == 0, f"{k}: in_channels {i} not divisible by groups {g}"
            # [in, out/g, kh, kw] -> [out, in/g, kh, kw], group-block aware
            arr = np.ascontiguousarray(
                arr.reshape(g, i // g, og, kh, kw)
                   .transpose(0, 2, 1, 3, 4)
                   .reshape(g * og, i // g, kh, kw))
        torch_items.append((k, arr))

    out: dict[str, np.ndarray] = {}
    used = set()
    name_map = dict(name_map or {})
    arrs = dict(torch_items)
    # explicit mappings first
    for tname, pname in name_map.items():
        assert tname in arrs, f"torch key {tname!r} not found"
        assert pname in shapes, f"param {pname!r} not in model"
        out[pname] = _fit(arrs[tname], shapes[pname], transpose_linear)
        used.add(tname)
    # then shape-order pairing
    remaining = [n for n in shapes if n not in out]
    for tname, arr in torch_items:
        if tname in used:
            continue
        for pname in remaining:
            fitted = _try_fit(arr, shapes[pname], transpose_linear)
            if fitted is not None:
                out[pname] = fitted
                remaining.remove(pname)
                used.add(tname)
                break
    missing = [n for n in shapes if n not in out]
    assert not missing, (
        f"unmatched parameters {missing}; provide name_map entries")
    return out


def _try_fit(arr: np.ndarray, shape: tuple, transpose_linear: bool):
    if tuple(arr.shape) == shape:
        return arr
    if transpose_linear and arr.ndim == 2 and tuple(arr.T.shape) == shape:
        return np.ascontiguousarray(arr.T)
    if arr.size == int(np.prod(shape)) and arr.ndim == 1:
        return arr.reshape(shape)
    # conv kernels: torch [O, I, kh, kw] -> this framework's [O, I*kh*kw]
    # (same element order — C-major within each output filter)
    if (arr.ndim == 4 and len(shape) == 2 and arr.shape[0] == shape[0]
            and arr.size == int(np.prod(shape))):
        return arr.reshape(shape)
    return None


def _fit(arr: np.ndarray, shape: tuple, transpose_linear: bool) -> np.ndarray:
    fitted = _try_fit(arr, shape, transpose_linear)
    assert fitted is not None, f"cannot fit {arr.shape} into {shape}"
    return fitted


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", required=True)
    p.add_argument("--torch", required=True, dest="torch_path")
    p.add_argument("--output", required=True)
    p.add_argument("--config_args", default="")
    args = p.parse_args(argv)

    import torch

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer import checkpoint as ckpt

    cfg = parse_config(args.config, args.config_args)
    sd = torch.load(args.torch_path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    params = convert_state_dict(sd, cfg.model_config)
    out = ckpt.save_checkpoint(args.output, 0, params,
                               config_json=cfg.to_json())
    print(f"wrote {out} ({len(params)} parameters)")


if __name__ == "__main__":
    main()
