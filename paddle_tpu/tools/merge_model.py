"""Bundle a trained checkpoint + config into one deployable file
(ref: paddle/trainer/MergeModel.cpp paddle_merge_model;
GradientMachine::create(istream) reads the bundle back,
GradientMachine.cpp:87-110).

Bundle = single .npz whose entries are the flattened params plus a
'__config__' JSON blob; loadable via load_bundle() or
api.GradientMachine.createFromFile().

CLI: python -m paddle_tpu.tools.merge_model --model_dir pass-00004 \\
         [--config trainer_config.py] --output model.paddle_tpu
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np


def merge_model(model_dir: str, output: str,
                config_path: str | None = None) -> str:
    """model_dir: a pass-%05d checkpoint dir (trainer/checkpoint.py)."""
    from paddle_tpu.trainer import checkpoint as ckpt

    data = ckpt.load_checkpoint(model_dir)
    entries = {f"params/{k}": np.asarray(v) for k, v in data["params"].items()}
    if config_path is not None:
        from paddle_tpu.config.parser import parse_config
        cfg = parse_config(config_path, "")
        config_json = cfg.to_json()
    else:
        config_json = data.get("config_json")
        assert config_json, (
            f"{model_dir} has no saved config; pass --config")
    entries["__config__"] = np.frombuffer(
        config_json.encode(), dtype=np.uint8)
    np.savez(output, **entries)
    if not output.endswith(".npz"):
        # np.savez appends .npz; keep the requested name
        os.replace(output + ".npz", output)
    return output


def load_bundle(path: str):
    """Returns (TrainerConfig, {param_name: np.ndarray})."""
    from paddle_tpu.config.schema import TrainerConfig

    data = np.load(path, allow_pickle=False)
    config_json = bytes(data["__config__"]).decode()
    cfg = TrainerConfig.from_json(config_json)
    params = {k[len("params/"):]: data[k] for k in data.files
              if k.startswith("params/")}
    return cfg, params


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_dir", required=True,
                   help="pass-%%05d checkpoint directory")
    p.add_argument("--config", default=None, help="config file to embed")
    p.add_argument("--output", required=True)
    args = p.parse_args(argv)
    out = merge_model(args.model_dir, args.output, args.config)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
