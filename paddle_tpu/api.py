"""Programmatic training/inference API — the swig_paddle equivalent.

Mirrors the reference's embedded-API surface (ref: paddle/api/PaddleAPI.h:
93-712 — Matrix/Vector/IVector, Arguments, Parameter, ParameterOptimizer,
GradientMachine, SequenceGenerator, Trainer; driven from Python via SWIG,
ref: paddle/api/Paddle.swig, demo/quick_start/api_train.py,
api/test/testTrain.py).

TPU-native re-design: the framework is already Python+JAX, so no FFI layer
is needed — these classes adapt the jitted GraphExecutor/ParameterUpdater
machinery to the reference's imperative API shape.  Two deliberate
semantic changes:
  * forwardBackward returns the whole gradient pytree (autodiff) instead
    of firing per-parameter UpdateCallbacks during backward — the XLA
    scheduler overlaps what the callback pipeline used to overlap;
  * ParameterOptimizer.update applies one whole-tree jitted update rather
    than per-parameter buffer mutation.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.schema import (
    ModelConfig, OptimizationConfig, TrainerConfig,
)
from paddle_tpu.data.feeder import make_batch
from paddle_tpu.data.provider import InputType
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import TEST, TRAIN
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.utils.flags import FLAGS, parse_flags

__all__ = [
    "initPaddle", "Matrix", "Vector", "IVector", "Arguments", "Parameter",
    "ParameterOptimizer", "GradientMachine", "SequenceGenerator", "Trainer",
    "DataProviderConverter",
]


def initPaddle(*args: str) -> None:
    """(ref: PaddleAPI.h initPaddle; TrainerMain initMain).  Accepts
    --flag=value strings and merges them into the global flag registry."""
    parse_flags(list(args))


# ---------------------------------------------------------------------------
# numpy-interop value wrappers (ref: PaddleAPI.h Matrix/Vector/IVector)
# ---------------------------------------------------------------------------

class Matrix:
    """2-D float matrix (ref: PaddleAPI.h:93 Matrix; numpy interop via
    copyToNumpyMat/createFromNumpyMat)."""

    def __init__(self, data: np.ndarray):
        self._d = np.asarray(data, np.float32)
        assert self._d.ndim == 2

    @staticmethod
    def createZero(height: int, width: int) -> "Matrix":
        return Matrix(np.zeros((height, width), np.float32))

    @staticmethod
    def createDense(data: Sequence[float], height: int, width: int) -> "Matrix":
        return Matrix(np.asarray(data, np.float32).reshape(height, width))

    @staticmethod
    def createFromNumpyMat(arr: np.ndarray) -> "Matrix":
        return Matrix(arr)

    def copyToNumpyMat(self) -> np.ndarray:
        return self._d.copy()

    def toNumpyMatInplace(self) -> np.ndarray:
        return self._d

    def getHeight(self) -> int:
        return self._d.shape[0]

    def getWidth(self) -> int:
        return self._d.shape[1]

    def get(self, i: int, j: int) -> float:
        return float(self._d[i, j])

    def set(self, i: int, j: int, v: float) -> None:
        self._d[i, j] = v


class Vector:
    """1-D float vector (ref: PaddleAPI.h Vector)."""

    def __init__(self, data: np.ndarray):
        self._d = np.asarray(data, np.float32).reshape(-1)

    @staticmethod
    def create(data: Sequence[float]) -> "Vector":
        return Vector(np.asarray(data, np.float32))

    @staticmethod
    def createZero(size: int) -> "Vector":
        return Vector(np.zeros(size, np.float32))

    @staticmethod
    def createFromNumpyArray(arr: np.ndarray) -> "Vector":
        return Vector(arr)

    def toNumpyArrayInplace(self) -> np.ndarray:
        return self._d

    def copyToNumpyArray(self) -> np.ndarray:
        return self._d.copy()

    def getSize(self) -> int:
        return self._d.size

    def __len__(self) -> int:
        return self._d.size


class IVector:
    """1-D int vector (ref: PaddleAPI.h IVector)."""

    def __init__(self, data: np.ndarray):
        self._d = np.asarray(data, np.int32).reshape(-1)

    @staticmethod
    def create(data: Sequence[int]) -> "IVector":
        return IVector(np.asarray(data, np.int32))

    @staticmethod
    def createZero(size: int) -> "IVector":
        return IVector(np.zeros(size, np.int32))

    @staticmethod
    def createFromNumpyArray(arr: np.ndarray) -> "IVector":
        return IVector(arr)

    def toNumpyArrayInplace(self) -> np.ndarray:
        return self._d

    def copyToNumpyArray(self) -> np.ndarray:
        return self._d.copy()

    def getSize(self) -> int:
        return self._d.size

    def __len__(self) -> int:
        return self._d.size


class Arguments:
    """Ordered slot collection, convertible to the executor's feed dict
    (ref: PaddleAPI.h Arguments: setSlotValue/getSlotValue/setSlotIds/
    sequenceStartPositions; here a slot is one Argument)."""

    def __init__(self, slots: Optional[list[Argument]] = None,
                 names: Optional[list[str]] = None):
        self.slots: list[Argument] = slots or []
        self.names: Optional[list[str]] = names

    @staticmethod
    def createArguments(size: int) -> "Arguments":
        return Arguments([Argument() for _ in range(size)])

    def getSlotNum(self) -> int:
        return len(self.slots)

    def resize(self, size: int) -> None:
        while len(self.slots) < size:
            self.slots.append(Argument())
        del self.slots[size:]

    def setSlotValue(self, idx: int, mat: Matrix) -> None:
        self.slots[idx] = self.slots[idx].replace(value=mat.toNumpyMatInplace())

    def setSlotIds(self, idx: int, ids: IVector) -> None:
        self.slots[idx] = self.slots[idx].replace(ids=ids.toNumpyArrayInplace())

    def setSlotSequenceStartPositions(self, idx: int, lengths: IVector) -> None:
        """Padded-dense re-design: per-sequence lengths, not start offsets."""
        self.slots[idx] = self.slots[idx].replace(
            lengths=lengths.toNumpyArrayInplace())

    def getSlotValue(self, idx: int) -> Matrix:
        v = np.asarray(self.slots[idx].value)
        return Matrix(v.reshape(v.shape[0], -1))

    def getSlotIds(self, idx: int) -> IVector:
        return IVector(np.asarray(self.slots[idx].ids).reshape(-1))

    def toFeed(self, input_names: Sequence[str]) -> dict[str, Argument]:
        names = self.names or list(input_names)[: len(self.slots)]
        return dict(zip(names, self.slots))


class DataProviderConverter:
    """samples -> Arguments (ref: py_paddle/dataprovider_converter.py)."""

    def __init__(self, input_types: Sequence[InputType],
                 names: Optional[Sequence[str]] = None):
        self.types = list(input_types)
        self.names = list(names) if names else None

    def __call__(self, samples: Sequence) -> Arguments:
        samples = list(samples)
        names = self.names or [f"slot{i}" for i in range(len(self.types))]
        batch = make_batch(samples, self.types, names)
        return Arguments([batch[n] for n in names], names=self.names)


# ---------------------------------------------------------------------------
# parameters & optimizer
# ---------------------------------------------------------------------------

class Parameter:
    """Handle to one named parameter inside a GradientMachine
    (ref: PaddleAPI.h Parameter: getName/getBuf/getConfig/getID)."""

    def __init__(self, machine: "GradientMachine", name: str, pid: int):
        self._m = machine
        self._name = name
        self._id = pid

    def getName(self) -> str:
        return self._name

    def getID(self) -> int:
        return self._id

    def getSize(self) -> int:
        return int(np.prod(self._m.params[self._name].shape))

    def getShape(self) -> tuple:
        return tuple(self._m.params[self._name].shape)

    def getValue(self) -> np.ndarray:
        return np.asarray(self._m.params[self._name])

    def setValue(self, arr: np.ndarray) -> None:
        cur = self._m.params[self._name]
        self._m.params[self._name] = jnp.asarray(
            np.asarray(arr, np.float32).reshape(cur.shape))

    def getConfig(self):
        return self._m.model.parameter(self._name)


class ParameterOptimizer:
    """Whole-tree optimizer handle (ref: PaddleAPI.h ParameterOptimizer,
    api/test/testTrain.py init_optimizers/update usage)."""

    def __init__(self, opt_config: OptimizationConfig, model: ModelConfig):
        from paddle_tpu.optim.updater import ParameterUpdater
        self._updater = ParameterUpdater(model, opt_config)
        self._state = None
        self._step = None

    @staticmethod
    def create(opt_config: OptimizationConfig,
               model: ModelConfig) -> "ParameterOptimizer":
        return ParameterOptimizer(opt_config, model)

    def init(self, params: dict[str, jax.Array]) -> None:
        self._state = self._updater.init_state(params)

    def startPass(self) -> None:
        if self._state is not None:
            self._state = self._updater.start_pass(self._state)

    def finishPass(self) -> None:
        if self._state is not None:
            self._state = self._updater.finish_pass(self._state)

    def update(self, params: dict, grads: dict, batch_size: int = 1) -> dict:
        """Apply one optimizer step; returns the new params."""
        assert self._state is not None, "call init() first"
        if self._step is None:
            self._step = jax.jit(self._updater.step,
                                 static_argnames=("batch_size",))
        new_params, self._state = self._step(params, grads, self._state,
                                             batch_size=batch_size)
        return new_params


# ---------------------------------------------------------------------------
# gradient machine
# ---------------------------------------------------------------------------

class GradientMachine:
    """forward/backward executor over one ModelConfig
    (ref: PaddleAPI.h GradientMachine:460-560, GradientMachine.cpp)."""

    def __init__(self, model: ModelConfig, seed: int = 1):
        self.model = model
        self.executor = GraphExecutor(model)
        self.params: dict[str, jax.Array] = {}
        self.net_state = self.executor.init_state()
        self._rng = jax.random.PRNGKey(seed)
        self._fwd = None
        self._fwdbwd = None
        self.randParameters(seed)

    @staticmethod
    def createFromConfigProto(model: ModelConfig, seed: int = 1) -> "GradientMachine":
        return GradientMachine(model, seed)

    @staticmethod
    def createFromFile(path: str) -> "GradientMachine":
        """Load a merged deploy bundle (tools/merge_model.py; ref:
        GradientMachine::create(istream), GradientMachine.cpp:87-110)."""
        from paddle_tpu.tools.merge_model import load_bundle
        cfg, params = load_bundle(path)
        m = GradientMachine(cfg.model_config)
        for name in m.params:
            assert name in params, f"bundle missing parameter {name!r}"
            m.params[name] = jnp.asarray(params[name])
        return m

    def randParameters(self, seed: int = 1) -> None:
        self.params = self.executor.init_params(jax.random.PRNGKey(seed))

    def getParameters(self) -> list[Parameter]:
        return [Parameter(self, name, i)
                for i, name in enumerate(sorted(self.params))]

    def getParameter(self, name: str) -> Parameter:
        names = sorted(self.params)
        return Parameter(self, name, names.index(name))

    def _feed(self, inArgs) -> dict[str, Argument]:
        if isinstance(inArgs, dict):
            return inArgs
        return inArgs.toFeed(self.model.input_layer_names)

    def forward(self, inArgs, passType: str = TEST) -> dict[str, Argument]:
        """Returns all layer outputs by name (ref: forward + getLayerOutput)."""
        if self._fwd is None:
            self._fwd = jax.jit(
                lambda p, f, s, r: self.executor.forward(p, f, s, mode=TEST, rng=r))
        self._rng, sub = jax.random.split(self._rng)
        outs, _, _ = self._fwd(self.params, self._feed(inArgs),
                               self.net_state, sub)
        return {k: v.flatten_image() if isinstance(v, Argument) else v
                for k, v in outs.items()}

    def forwardTest(self, inArgs) -> dict[str, Argument]:
        return self.forward(inArgs, TEST)

    def forwardBackward(self, inArgs,
                        callback: Optional[Callable] = None):
        """Returns (mean cost, gradient pytree); optionally fires
        callback(name, grad) per parameter afterwards — the sequential
        analog of the reference's pipelined UpdateCallback."""
        if self._fwdbwd is None:
            def _f(p, f, s, r):
                (loss, _), grads = jax.value_and_grad(
                    self.executor.loss, has_aux=True)(p, f, s, TRAIN, r)
                return loss, grads
            self._fwdbwd = jax.jit(_f)
        self._rng, sub = jax.random.split(self._rng)
        loss, grads = self._fwdbwd(self.params, self._feed(inArgs),
                                   self.net_state, sub)
        if callback is not None:
            for name in sorted(grads):
                callback(name, grads[name])
        return float(loss), grads

    def getLayerOutput(self, name: str, inArgs) -> Argument:
        return self.forward(inArgs)[name]

    # -- persistence (ref: GradientMachine::saveParameters/loadParameters) --
    def saveParameters(self, directory: str) -> None:
        from paddle_tpu.trainer import checkpoint as ckpt
        ckpt.save_checkpoint(directory, 0, jax.device_get(self.params),
                             None, self.net_state,
                             config_json=self.model.to_json())

    def loadParameters(self, path: str) -> None:
        from paddle_tpu.trainer import checkpoint as ckpt
        data = ckpt.load_checkpoint(path)
        for name in self.params:
            assert name in data["params"], f"missing parameter {name!r}"
            self.params[name] = jnp.asarray(data["params"][name])


class SequenceGenerator:
    """Beam-search generation handle (ref: PaddleAPI.h SequenceGenerator;
    RecurrentGradientMachine::generateSequence)."""

    def __init__(self, machine: GradientMachine, beam_size: Optional[int] = None,
                 max_length: Optional[int] = None):
        self._m = machine
        self._beam = beam_size
        self._maxlen = max_length

    def generate(self, inArgs):
        """Returns (ids [B, K, L], scores [B, K]) — beams best-first."""
        from paddle_tpu.graph.generator import generate
        feed = self._m._feed(inArgs)
        self._m._rng, sub = jax.random.split(self._m._rng)
        return generate(self._m.executor, self._m.params, feed, rng=sub,
                        beam_size=self._beam, max_length=self._maxlen)


class Trainer:
    """Imperative train/test driver over the high-level trainer
    (ref: PaddleAPI.h Trainer:640-712; api_train.py usage)."""

    def __init__(self, config: TrainerConfig, machine: Optional[GradientMachine] = None,
                 seed: int = 1):
        from paddle_tpu.trainer.trainer import Trainer as _Trainer
        self._t = _Trainer(config, seed=seed)
        if machine is not None:
            self._t.params = machine.params
        self._machine = machine
        self._pass_costs: list[float] = []

    @staticmethod
    def create(config: TrainerConfig,
               machine: Optional[GradientMachine] = None) -> "Trainer":
        return Trainer(config, machine)

    def startTrain(self) -> None:
        pass

    def finishTrain(self) -> None:
        if self._machine is not None:
            self._machine.params = self._t.params

    def startTrainPass(self) -> None:
        self._pass_costs = []

    def finishTrainPass(self) -> None:
        if self._machine is not None:
            self._machine.params = self._t.params

    def trainOneDataBatch(self, size: int, inArgs) -> float:
        feed = (inArgs if isinstance(inArgs, dict)
                else inArgs.toFeed(self._t.model.input_layer_names))
        cost = self._t.train_one_batch(feed)
        self._pass_costs.append(cost)
        return cost

    def startTestPeriod(self) -> None:
        self._test_costs: list[float] = []

    def testOneDataBatch(self, size: int, inArgs) -> float:
        feed = (inArgs if isinstance(inArgs, dict)
                else inArgs.toFeed(self._t.model.input_layer_names))
        if not hasattr(self, "_eval_fn"):
            ex = self._t.executor
            self._eval_fn = jax.jit(
                lambda p, f, s, r: ex.loss(p, f, s, TEST, r)[0])
        self._t.rng, sub = jax.random.split(self._t.rng)
        loss = self._eval_fn(self._t.params, feed, self._t.net_state, sub)
        self._test_costs.append(float(loss))
        return float(loss)

    def finishTestPeriod(self) -> float:
        return float(np.mean(self._test_costs)) if self._test_costs else 0.0

    def getPassCost(self) -> float:
        return float(np.mean(self._pass_costs)) if self._pass_costs else 0.0
