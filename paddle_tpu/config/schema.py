"""Model / trainer configuration schema.

This is the framework's serialized model description — the TPU-native
equivalent of the reference's protobuf schema (ref: proto/ModelConfig.proto.m4,
TrainerConfig.proto.m4, ParameterConfig.proto.m4, DataConfig.proto.m4).  The
reference funnels every model through a `ModelConfig` proto built by a Python
DSL and consumed by the C++ graph builder; here the same role is played by
plain typed dataclasses with JSON round-tripping (the graph builder is Python
→ XLA, so protobuf buys nothing but friction).

Field names deliberately track the reference's names (type strings, size
inference, sub-model structure) so configs translate 1:1 conceptually, while
the *representation* is idiomatic Python.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from dataclasses import dataclass, field
from typing import Any, Optional


# ---------------------------------------------------------------------------
# generic (de)serialization for the whole schema tree
# ---------------------------------------------------------------------------

def _to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj):
        out = {"__type__": type(obj).__name__}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None or v == f.default:
                continue
            out[f.name] = _to_dict(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [_to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _to_dict(v) for k, v in obj.items()}
    return obj


_SCHEMA_TYPES: dict[str, type] = {}


def _schema(cls):
    _SCHEMA_TYPES[cls.__name__] = cls
    return cls


def _from_dict(data: Any) -> Any:
    if isinstance(data, dict) and "__type__" in data:
        cls = _SCHEMA_TYPES[data["__type__"]]
        kwargs = {k: _from_dict(v) for k, v in data.items() if k != "__type__"}
        return cls(**kwargs)
    if isinstance(data, list):
        return [_from_dict(v) for v in data]
    if isinstance(data, dict):
        return {k: _from_dict(v) for k, v in data.items()}
    return data


class _Serializable:
    def to_dict(self) -> dict:
        return _to_dict(self)

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, data: dict) -> "Any":
        obj = _from_dict(data)
        assert isinstance(obj, cls), f"expected {cls.__name__}, got {type(obj).__name__}"
        return obj

    @classmethod
    def from_json(cls, text: str) -> "Any":
        return cls.from_dict(json.loads(text))


# ---------------------------------------------------------------------------
# parameters (ref: proto/ParameterConfig.proto.m4)
# ---------------------------------------------------------------------------

@_schema
@dataclass
class ParameterConfig(_Serializable):
    """Trainable parameter description (ref: ParameterConfig.proto.m4:25-80)."""

    name: str = ""
    size: int = 0
    dims: list[int] = field(default_factory=list)
    learning_rate: float = 1.0          # per-parameter LR multiplier
    momentum: Optional[float] = None    # None = use global momentum
    initial_mean: float = 0.0
    initial_std: float = 0.01
    # 'normal' | 'uniform' | 'zero'; with initial_smart, std is scaled 1/sqrt(fan_in)
    # (ref: config_parser.py smart initialization; ParameterConfig initial_strategy)
    initial_strategy: str = "normal"
    initial_smart: bool = False
    # None = inherit the global setting; 0.0 = explicitly disabled
    decay_rate: Optional[float] = None       # L2 (ref: decay_rate)
    decay_rate_l1: Optional[float] = None    # L1
    is_static: bool = False             # frozen parameter
    is_shared: bool = False
    sparse_update: bool = False         # row-sparse gradient path (embeddings)
    gradient_clipping_threshold: Optional[float] = None
    # TPU additions: sharding spec over mesh axes, e.g. ["model", None]
    partition_spec: Optional[list] = None
    dtype: str = "float32"
    # updater hooks (ref: ParameterUpdaterHook.cpp:32,167 StaticPruningHook):
    # e.g. [{"type": "pruning", "sparsity_ratio": 0.6}] or
    # [{"type": "pruning", "mask_filename": "mask.npy"}]
    update_hooks: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# projections & operators inside mixed layers
# (ref: ModelConfig.proto.m4 ProjectionConfig:190, OperatorConfig:212)
# ---------------------------------------------------------------------------

@_schema
@dataclass
class ConvConfig(_Serializable):
    """Convolution geometry (ref: ModelConfig.proto.m4 ConvConfig)."""

    filter_size: int = 3
    filter_size_y: int = 0          # 0 → square (= filter_size)
    channels: int = 1
    stride: int = 1
    stride_y: int = 0
    padding: int = 0
    padding_y: int = 0
    groups: int = 1
    img_size: int = 0               # input spatial size (square)
    img_size_y: int = 0
    output_x: int = 0               # inferred output spatial size
    output_y: int = 0
    caffe_mode: bool = True         # output-size rounding mode (ref: MathUtils.cpp outputSize)


@_schema
@dataclass
class PoolConfig(_Serializable):
    """Pooling geometry (ref: ModelConfig.proto.m4 PoolConfig)."""

    pool_type: str = "max-projection"   # 'max-projection' | 'avg-projection' | ...
    channels: int = 1
    size_x: int = 2
    size_y: int = 0
    stride: int = 2
    stride_y: int = 0
    padding: int = 0
    padding_y: int = 0
    img_size: int = 0
    img_size_y: int = 0
    output_x: int = 0
    output_y: int = 0


@_schema
@dataclass
class NormConfig(_Serializable):
    """Local response norm geometry (ref: ModelConfig.proto.m4 NormConfig)."""

    norm_type: str = "cmrnorm-projection"
    channels: int = 1
    size: int = 5
    scale: float = 0.0019531
    pow: float = 0.75
    img_size: int = 0
    img_size_y: int = 0
    output_x: int = 0
    output_y: int = 0


@_schema
@dataclass
class ProjectionConfig(_Serializable):
    """A parameterized linear-ish map inside a mixed layer
    (ref: ProjectionConfig types: identity, dot_mul, full_matrix, table,
    context, trans_full_matrix, conv)."""

    type: str = "fc"
    name: str = ""
    input_size: int = 0
    output_size: int = 0
    # context projection (ref: ContextProjection, hl_context_projection_*)
    context_start: int = 0
    context_length: int = 0
    trainable_padding: bool = False
    # conv projection
    conv: Optional[ConvConfig] = None
    num_filters: int = 0


@_schema
@dataclass
class OperatorConfig(_Serializable):
    """A parameter-free multi-input op inside a mixed layer
    (ref: OperatorConfig: dot_mul, conv)."""

    type: str = "dot_mul"
    input_indices: list[int] = field(default_factory=list)
    input_sizes: list[int] = field(default_factory=list)
    output_size: int = 0
    dotmul_scale: float = 1.0
    conv: Optional[ConvConfig] = None
    num_filters: int = 0


# ---------------------------------------------------------------------------
# layers (ref: ModelConfig.proto.m4 LayerConfig:262)
# ---------------------------------------------------------------------------

@_schema
@dataclass
class LayerInput(_Serializable):
    """One input edge of a layer (ref: LayerInputConfig)."""

    input_layer_name: str = ""
    input_parameter_name: str = ""
    proj: Optional[ProjectionConfig] = None


@_schema
@dataclass
class LayerConfig(_Serializable):
    """One node of the model graph (ref: ModelConfig.proto.m4 LayerConfig:262).

    Type-specific geometry lives in the typed sub-configs (conv/pool/norm) or
    the open `attrs` dict — mirroring the proto's optional-field sprawl
    without freezing every layer's fields into the core schema.
    """

    name: str = ""
    type: str = ""
    size: int = 0
    active_type: str = ""               # activation registry key ('' = identity)
    inputs: list[LayerInput] = field(default_factory=list)
    bias_parameter_name: str = ""       # '' = no bias
    operators: list[OperatorConfig] = field(default_factory=list)
    drop_rate: float = 0.0
    # image layers
    conv: Optional[ConvConfig] = None
    pool: Optional[PoolConfig] = None
    norm: Optional[NormConfig] = None
    num_filters: int = 0
    shared_biases: bool = False
    # batch norm
    use_global_stats: Optional[bool] = None
    moving_average_fraction: float = 0.9
    # cost layers
    coeff: float = 1.0
    num_classes: int = 0                # NCE / hsigmoid / CRF tag count
    softmax_selfnorm_alpha: float = 0.1
    neg_sampling_dist: Optional[list] = None
    num_neg_samples: int = 10
    # sequence layers
    trans_type: str = "non-seq"         # 'seq' | 'non-seq' (expand/seqpool levels)
    seq_pool_type: str = ""             # max/average/last/first for seqpool layers
    average_strategy: str = "average"   # 'average'|'sum'|'squarerootn'
    select_first: bool = False
    stride: int = -1
    reversed: bool = False              # recurrent direction
    # misc
    beam_size: int = 0
    blank: int = 0                      # CTC blank id
    norm_by_times: bool = False
    add_size: int = 0
    delimited: bool = True
    device: int = -1
    attrs: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# recurrent groups / generation (ref: SubModelConfig:477-503)
# ---------------------------------------------------------------------------

@_schema
@dataclass
class MemoryConfig(_Serializable):
    """A recurrent memory edge: layer output fed back at t+1
    (ref: SubModelConfig.memories; config_parser.py Memory)."""

    link_name: str = ""                 # in-group layer whose output is remembered
    layer_name: str = ""                # the agent layer that reads it at t
    boot_layer_name: str = ""           # optional initial state source (outside group)
    boot_bias: bool = False
    boot_bias_active_type: str = ""
    boot_with_const_id: Optional[int] = None
    size: int = 0
    is_sequence: bool = False


@_schema
@dataclass
class GeneratorConfig(_Serializable):
    """Sequence-generation settings (ref: SubModelConfig.generator)."""

    max_num_frames: int = 100
    beam_size: int = 1
    eos_layer_name: str = ""
    eos_id: int = 0
    bos_id: int = 0
    num_results_per_sample: int = 1
    log_prob: bool = True
    # in-group layer producing the next-token distribution (scored by search)
    prob_layer_name: str = ""
    # memory carrying the previously generated id (fed back each step)
    id_memory_layer_name: str = ""


@_schema
@dataclass
class SubModelConfig(_Serializable):
    """A recurrent layer group: a sub-graph unrolled over time by the executor
    (ref: SubModelConfig:477-503; RecurrentGradientMachine)."""

    name: str = ""
    layer_names: list[str] = field(default_factory=list)
    input_layer_names: list[str] = field(default_factory=list)
    output_layer_names: list[str] = field(default_factory=list)
    # scan-carried state edges
    memories: list[MemoryConfig] = field(default_factory=list)
    # out-of-group → in-group data links (sequence consumed per timestep)
    in_links: list[str] = field(default_factory=list)
    in_link_layers: list[str] = field(default_factory=list)  # in-group alias layer per link
    # non-sequence inputs broadcast to every timestep (ref: StaticInput)
    static_links: list[str] = field(default_factory=list)
    static_link_layers: list[str] = field(default_factory=list)
    out_links: list[str] = field(default_factory=list)
    is_recurrent_layer_group: bool = False
    reversed: bool = False
    generator: Optional[GeneratorConfig] = None
    # enclosing recurrent group ('' = top level).  A nested group runs inside
    # its parent's scan step (ref: RecurrentGradientMachine.cpp:626-699 —
    # hierarchical RNN over sub-sequences)
    parent: str = ""


@_schema
@dataclass
class EvaluatorConfig(_Serializable):
    """Metric attached to the graph (ref: ModelConfig.proto.m4 EvaluatorConfig:418)."""

    name: str = ""
    type: str = "classification_error"
    input_layer_names: list[str] = field(default_factory=list)
    num_chunk_types: int = 0
    chunk_scheme: str = ""
    classification_threshold: float = 0.5
    positive_label: int = -1
    excluded_chunk_types: list[int] = field(default_factory=list)
    # printers (ref: EvaluatorConfig result_file/dict_file/delimited)
    result_file: str = ""
    dict_file: str = ""
    delimited: bool = True


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

@_schema
@dataclass
class ModelConfig(_Serializable):
    """The whole graph (ref: ModelConfig.proto.m4:505-531)."""

    type: str = "nn"                    # 'nn' | 'recurrent_nn' (has sub-models)
    layers: list[LayerConfig] = field(default_factory=list)
    parameters: list[ParameterConfig] = field(default_factory=list)
    input_layer_names: list[str] = field(default_factory=list)
    output_layer_names: list[str] = field(default_factory=list)
    evaluators: list[EvaluatorConfig] = field(default_factory=list)
    sub_models: list[SubModelConfig] = field(default_factory=list)

    def layer(self, name: str) -> LayerConfig:
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(f"no layer named {name!r}")

    def parameter(self, name: str) -> ParameterConfig:
        for p in self.parameters:
            if p.name == name:
                return p
        raise KeyError(f"no parameter named {name!r}")


# ---------------------------------------------------------------------------
# optimization / trainer / data configs (ref: TrainerConfig.proto.m4:20-132)
# ---------------------------------------------------------------------------

@_schema
@dataclass
class OptimizationConfig(_Serializable):
    """Optimizer + schedule settings (ref: TrainerConfig.proto.m4 OptimizationConfig)."""

    batch_size: int = 1
    algorithm: str = "sgd"              # 'sgd' (others like 'owlqn' dropped: superseded)
    learning_method: str = "momentum"   # momentum|adagrad|adadelta|rmsprop|decayed_adagrad|adam|adamax|sparse_momentum
    learning_rate: float = 1.0
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"  # constant|poly|caffe_poly|exp|discexp|linear|manual|pass_manual
    learning_rate_args: str = ""
    momentum: float = 0.0
    ada_epsilon: float = 1e-6
    ada_rho: float = 0.95
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    l1_weight: float = 0.0
    l2_weight: float = 0.0
    gradient_clipping_threshold: float = 0.0
    average_window: float = 0.0         # ModelAverage window fraction
    max_average_window: int = 0
    do_average_in_cpu: bool = False
    delta_add_rate: float = 1.0
    num_batches_per_send_parameter: int = 1
    num_batches_per_get_parameter: int = 1
    shrink_parameter_value: float = 0.0
    # TPU additions
    dtype: str = "float32"              # param dtype
    compute_dtype: str = ""             # '' = same as dtype; 'bfloat16' for MXU speed
    # GPipe microbatches per batch for config-driven pipeline parallelism
    # (layers annotated device=N); 0 = one microbatch per pipeline stage
    pipeline_micro_batches: int = 0
    # 'gpipe' (all-forward then autodiff backward; in-flight activations
    # grow with the microbatch count), '1f1b' (one-forward-one-backward
    # with per-stage recompute; in-flight boundary carriers capped at the
    # stage count — the schedule for microbatch counts >> stages), or
    # 'interleaved' (1F1B over virtual stages: annotate device=0..S*v-1,
    # chunks placed round-robin so each device hosts v non-contiguous
    # chunks — the warmup bubble shrinks ~v-fold)
    pipeline_schedule: str = "gpipe"
    # virtual stages per device for pipeline_schedule='interleaved'
    pipeline_virtual_stages: int = 1
    # ZeRO-1: shard optimizer slot buffers over the data axis (the pserver
    # design where each server updates 1/N of every parameter — here XLA
    # keeps the update sharded and gathers only the fresh params)
    shard_optimizer_state: bool = False
    # ZeRO stage over the data axis (generalizes shard_optimizer_state):
    #   0 = off (or 1 if shard_optimizer_state is set)
    #   1 = optimizer slots sharded
    #   2 = + gradients reduce-scattered to the same shards
    #   3 = + parameters stored sharded (FSDP; gathered on use by XLA)
    zero_stage: int = 0


@_schema
@dataclass
class DataConfig(_Serializable):
    """Data source description (ref: DataConfig.proto.m4; define_py_data_sources2)."""

    type: str = "py2"                   # 'py2' | 'ptsh' | 'multi'
    files: str = ""                     # file-list path or glob
    load_data_module: str = ""
    load_data_object: str = ""
    load_data_args: str = ""
    async_load_data: bool = True
    constant_slots: list[float] = field(default_factory=list)
    # type='multi' (ref: MultiDataProvider.{h,cpp}): sub-sources mixed by
    # data ratio into one stream
    sub_configs: list["DataConfig"] = field(default_factory=list)
    data_ratios: list[int] = field(default_factory=list)


@_schema
@dataclass
class TrainerConfig(_Serializable):
    """Top-level config (ref: TrainerConfig.proto.m4:132)."""

    model_config: Optional[ModelConfig] = None
    opt_config: Optional[OptimizationConfig] = None
    data_config: Optional[DataConfig] = None
    test_data_config: Optional[DataConfig] = None
    save_dir: str = "./output"
