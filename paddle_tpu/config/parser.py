"""Config parser: run a user config file and return its TrainerConfig.

TPU-native analog of the reference's config_parser entry points
(ref: python/paddle/trainer/config_parser.py:3349 parse_config /
parse_config_and_serialize: executes the user config with execfile inside a
managed namespace and returns the assembled proto).  Here the user config is a
plain Python file importing paddle_tpu.dsl; executing it against a fresh
ConfigContext yields the TrainerConfig dataclass tree.
"""

from __future__ import annotations

import runpy
from typing import Optional

from paddle_tpu.config.schema import TrainerConfig
from paddle_tpu.dsl.base import config_context


def parse_config_args(config_args: str) -> dict[str, str]:
    """'a=1,b=2' -> {'a': '1', 'b': '2'} (ref: config_parser.py:3362-3366)."""
    out: dict[str, str] = {}
    if not config_args:
        return out
    for pair in config_args.split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        out[k.strip()] = v.strip()
    return out


def parse_config(config_file: str, config_args: str = "") -> TrainerConfig:
    """Execute `config_file` and collect the model/optimization/data configs.

    The config reads `get_config_arg(name, type, default)` for --config_args
    passthrough, exactly like the reference.
    """
    args = parse_config_args(config_args)

    def get_config_arg(name: str, type_=str, default=None):
        if name in args:
            if type_ is bool:
                return args[name].lower() in ("1", "true", "yes")
            return type_(args[name])
        return default

    with config_context() as ctx:
        runpy.run_path(config_file, init_globals={"get_config_arg": get_config_arg})
        return ctx.to_trainer_config()


def parse_config_and_serialize(config_file: str, config_args: str = "") -> str:
    """(ref: config_parser.py parse_config_and_serialize) — JSON instead of
    binary proto."""
    return parse_config(config_file, config_args).to_json()


def parse_config_callable(fn, *fn_args, **fn_kwargs) -> TrainerConfig:
    """Build a config by calling a Python function instead of a file."""
    with config_context() as ctx:
        fn(*fn_args, **fn_kwargs)
        return ctx.to_trainer_config()
