"""Fused Bahdanau additive-attention step in Pallas (TPU).

The seq2seq decoder's per-timestep hot path (ref: the reference's
simple_attention composite, networks.py:1257) is bandwidth-bound inside the
training scan (PERF.md: prefix-hoisting LOST 13% — the win is fewer
bytes/step, not fewer flops).  XLA already fuses the single-expression
formulation (ops/attention.py:additive_attention_step) well; this kernel
goes one step further and keeps the whole [bT, D] tanh/score tile in VMEM:

  grid (B/bB, T/bT), T innermost sequential: per tile compute
  tanh(enc_proj + u)·v scores, fold them into a running online-softmax
  (max, sum, context-acc) held in VMEM scratch, and emit context = acc/sum
  at the last tile.  enc_proj and enc_seq are each read from HBM exactly
  once; no [B, T, D] intermediate (tanh activations, scores, weights) is
  ever written back.

Key-validity comes from a [B, 128] broadcast-lengths column (not a [B, T]
mask): a 2-D mask block would pin the T tile to 128 lanes, padding T=30
decoder benches 4x; with lengths in a fixed 128-lane column the T tile
only needs sublane alignment (8 fp32 / 16 bf16 — the bf16 minimum follows
the same rule ADVICE flagged for the flash kernel).

Backward: custom_vjp that recomputes through the jnp reference formulation
— the step is tiny relative to the decoder GRU, and the training scan
already remats its whole body, so a hand-written backward kernel would
only duplicate what jax.vjp emits fused.

The u = dec_state @ w projection stays OUTSIDE the kernel: it is one MXU
matmul XLA fuses into the surrounding step; the kernel fuses what XLA will
not — the [B, T, D]-shaped elementwise/softmax/reduce chain.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.utils.jax_compat import pallas_tpu_compiler_params

Array = jax.Array

_NEG_INF = -1e30


def supported(backend: Optional[str] = None) -> bool:
    if os.environ.get("PADDLE_TPU_PALLAS", "1") == "0":
        return False
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return True
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _kernel(bB, bT, u_ref, v_ref, proj_ref, seq_ref, len_ref,
            out_ref, m_s, l_s, acc_s):
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    u = u_ref[...].astype(jnp.float32)                    # [bB, D]
    h = jnp.tanh(proj_ref[...].astype(jnp.float32) + u[:, None, :])
    D = h.shape[-1]
    # [bB*bT, D] @ [D, 1] on the MXU -> scores [bB, bT]
    # HIGHEST: on hardware the MXU's default fp32 path is a single bf16
    # pass (~1e-2 relative) — these dots are vector-sized (N=1 / M=1), so
    # full fp32 costs nothing and keeps the kernel's fp32 contract honest
    s = jax.lax.dot_general(
        h.reshape(bB * bT, D), v_ref[...].astype(jnp.float32).reshape(D, 1),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).reshape(bB, bT)
    # validity: global t index < length (lengths ride a [bB, 128] column)
    tpos = it * bT + jax.lax.broadcasted_iota(jnp.int32, (bB, bT), 1)
    valid = tpos < len_ref[:, :1].astype(jnp.int32)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev, l_prev = m_s[:, :1], l_s[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)         # [bB, bT]
    corr = jnp.exp(m_prev - m_new)
    l_s[:, :1] = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(                              # [bB, 1, Dv]
        p[:, None, :], seq_ref[...].astype(jnp.float32),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)
    acc_s[:] = acc_s[:] * corr + pv[:, 0, :]
    m_s[:, :1] = m_new

    @pl.when(it == nt - 1)
    def _():
        l = l_s[:, :1]
        out_ref[...] = (acc_s[:] / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)


def _fwd_pallas(u, v, enc_proj, enc_seq, lengths):
    B, T, D = enc_proj.shape
    Dv = enc_seq.shape[-1]
    from paddle_tpu.utils.dtypes import sublane_min
    sub = sublane_min(u, enc_proj, enc_seq)
    bB = _round_up(min(16, _round_up(B, sub)), sub)
    bT = _round_up(min(512, _round_up(T, sub)), sub)
    Bp, Tp = _round_up(B, bB), _round_up(T, bT)
    Dp, Dvp = _round_up(D, 128), _round_up(Dv, 128)
    # zero-padding is inert: padded D columns of u/enc_proj contribute
    # tanh(0+0)=0 times v's zero pad to the scores; padded T rows are
    # invalid via lengths; padded Dv columns are sliced off the output
    u = jnp.pad(u, ((0, Bp - B), (0, Dp - D)))
    v = jnp.pad(v.reshape(1, -1), ((0, 0), (0, Dp - D)))
    enc_proj = jnp.pad(enc_proj, ((0, Bp - B), (0, Tp - T), (0, Dp - D)))
    enc_seq = jnp.pad(enc_seq, ((0, Bp - B), (0, Tp - T), (0, Dvp - Dv)))
    len_col = jnp.broadcast_to(
        jnp.pad(lengths.astype(jnp.float32), (0, Bp - B))[:, None], (Bp, 128))

    kernel = functools.partial(_kernel, bB, bT)
    out = pl.pallas_call(
        kernel,
        grid=(Bp // bB, Tp // bT),
        in_specs=[
            pl.BlockSpec((bB, Dp), lambda ib, it: (ib, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), lambda ib, it: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bB, bT, Dp), lambda ib, it: (ib, it, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bB, bT, Dvp), lambda ib, it: (ib, it, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bB, 128), lambda ib, it: (ib, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bB, Dvp), lambda ib, it: (ib, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Bp, Dvp), enc_seq.dtype),
        scratch_shapes=[
            pltpu.VMEM((bB, 128), jnp.float32),   # running max (lane 0)
            pltpu.VMEM((bB, 128), jnp.float32),   # running sum (lane 0)
            pltpu.VMEM((bB, Dvp), jnp.float32),   # context accumulator
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(u, v, enc_proj, enc_seq, len_col)
    return out[:B, :Dv]


def _reference(dec_state, w, v, enc_proj, enc_seq, lengths):
    from paddle_tpu.ops.attention import additive_attention_step as ref
    T = enc_proj.shape[1]
    mask = jnp.arange(T)[None, :] < lengths.astype(jnp.int32)[:, None]
    return ref(dec_state, w, v, enc_proj, enc_seq, mask)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _fused(dec_state, w, v, enc_proj, enc_seq, lengths):
    # keep the (tiny) state projection in fp32 — the kernel folds it into
    # fp32 scores anyway, and a bf16 round-trip here costs real accuracy
    # against the reference formulation; HIGHEST because the MXU's default
    # single-bf16-pass on these fp32 operands alone exceeds the fp32
    # parity tolerance (v5e round-4 parity, additive_1 case)
    u = jnp.matmul(dec_state.astype(jnp.float32), w.astype(jnp.float32),
                   precision=jax.lax.Precision.HIGHEST)
    return _fwd_pallas(u, v, enc_proj, enc_seq, lengths)


def _vjp_fwd(dec_state, w, v, enc_proj, enc_seq, lengths):
    out = _fused(dec_state, w, v, enc_proj, enc_seq, lengths)
    return out, (dec_state, w, v, enc_proj, enc_seq, lengths)


def _vjp_bwd(res, g):
    dec_state, w, v, enc_proj, enc_seq, lengths = res
    _, vjp = jax.vjp(_reference, dec_state, w, v, enc_proj, enc_seq,
                     lengths)
    d_dec, d_w, d_v, d_proj, d_seq, _ = vjp(g)
    return d_dec, d_w, d_v, d_proj, d_seq, jnp.zeros_like(lengths)


_fused.defvjp(_vjp_fwd, _vjp_bwd)


def additive_attention_step(
    dec_state: Array,
    w: Array,
    v: Array,
    enc_proj: Array,
    enc_seq: Array,
    mask: Optional[Array] = None,
    lengths: Optional[Array] = None,
) -> Array:
    """Pallas-fused additive attention step; same contract as
    ops/attention.py:additive_attention_step.

    The kernel is lengths-based: it reads the mask only as a per-row
    valid-prefix count.  Callers that statically know their mask is a
    length prefix (the graph layer derives it from Argument lengths)
    should pass `lengths` directly — no guard, no mask materialization.
    A caller-supplied `mask` instead goes through a runtime
    prefix-contiguity check (lax.cond) and falls back to the dense path
    when it isn't a prefix (or has an all-invalid row, where the dense
    path returns the uniform average), so the public mask contract
    really is the dense one.
    """
    B, T, _ = enc_proj.shape
    if lengths is not None:
        assert mask is None, "pass mask or lengths, not both"
        return _fused(dec_state, w, v, enc_proj, enc_seq,
                      lengths.astype(jnp.float32))
    if mask is None:
        full = jnp.full((B,), T, jnp.float32)
        return _fused(dec_state, w, v, enc_proj, enc_seq, full)
    m = mask.astype(bool)
    lens = jnp.sum(m.astype(jnp.float32), axis=-1)
    prefix = jnp.arange(T)[None, :] < lens.astype(jnp.int32)[:, None]
    kernel_ok = jnp.logical_and(jnp.all(m == prefix), jnp.all(lens > 0))
    from paddle_tpu.ops.attention import additive_attention_step as dense
    return jax.lax.cond(
        kernel_ok,
        lambda: _fused(dec_state, w, v, enc_proj, enc_seq, lens),
        lambda: dense(dec_state, w, v, enc_proj, enc_seq, m).astype(
            enc_seq.dtype))
