"""Flash attention in Pallas (TPU) — fused online-softmax attention.

TPU-native "hot op" for the long-context path (NEW capability beyond the
reference, whose closest analog is the additive simple_attention composite,
ref: python/paddle/trainer_config_helpers/networks.py:1257).  The scan-based
`ops/attention.py:blockwise_attention` stays as the portable fallback; this
kernel computes the same math with the score tile resident in VMEM:

  forward   — grid (B*H, Tq/Bq, Tk/Bk): for one query tile, fold key/value
              tiles into the running (max, sum, acc) online-softmax state
              held in VMEM scratch across the sequential innermost grid
              axis; one [Bq,D]x[D,Bk] + one [Bq,Bk]x[Bk,D] MXU matmul per
              tile, no [Tq,Tk] score matrix in HBM.
  backward  — custom_vjp (FlashAttention-2 style): the forward saves only
              the per-row log-sum-exp; two kernels recompute the score
              tiles and produce dq (grid over q tiles) and dk/dv (grid
              over k tiles).  delta = rowsum(do * o) is precomputed, and an
              lse cotangent (from a ring combine) folds into it as
              delta - dlse, since dlse/ds_j = p_j.

Masking matches `dot_product_attention`: per-sequence key validity +
causality, fully-masked rows output exactly 0 with lse = -inf (so a ring
combine weighs them out naturally; the backward kernels' validity mask
already zeroes their p).  Query-row validity is applied OUTSIDE the kernel
(out *= q_mask): the zeroed cotangent then kills all gradient contributions
of invalid rows.

`q_offset` / `k_offset` (SMEM scalars, may be traced) globalize the causal
positions so a ring/context-parallel caller can run the kernel on one
(q-shard, k-shard) pair of a longer sequence — see
`ops/attention.py:ring_attention`'s flash path.

Head dim and sequence lengths are zero-padded to tile multiples (lane dim
128); zero k/v padding columns are inert in the dot products and padded key
rows are masked invalid.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_NEG_INF = -1e30


def supported(backend: Optional[str] = None) -> bool:
    """Whether the pallas flash kernel may be used."""
    if os.environ.get("PADDLE_TPU_PALLAS", "1") == "0":
        return False
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return True
    # off-TPU the kernel only runs in (slow) interpret mode — opt-in for tests
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _tile_mask(kv_row, q_off, k_off, iq, ik, Bq, Bk, causal, window):
    """[Bq, Bk] validity of one score tile: key validity x causality x
    sliding window, on GLOBAL positions (offsets cover ring/context-parallel
    shards)."""
    mask = jnp.broadcast_to((kv_row > 0.0)[None, :], (Bq, Bk))
    if causal or window is not None:
        qpos = q_off + iq * Bq + jax.lax.broadcasted_iota(
            jnp.int32, (Bq, Bk), 0)
        kpos = k_off + ik * Bk + jax.lax.broadcasted_iota(
            jnp.int32, (Bq, Bk), 1)
        if causal:
            mask = jnp.logical_and(mask, kpos <= qpos)
        if window is not None:
            mask = jnp.logical_and(mask, jnp.abs(qpos - kpos) < window)
    return mask


def _tile_live(q_off, k_off, iq, ik, Bq, Bk, causal, window):
    """False iff causality/window masks the ENTIRE tile — those tiles skip
    both matmuls (halves long-causal work; makes sliding-window cost
    O(T * window) instead of O(T^2))."""
    q_lo = q_off + iq * Bq
    q_hi = q_lo + Bq - 1
    k_lo = k_off + ik * Bk
    k_hi = k_lo + Bk - 1
    live = True
    if causal:
        live = jnp.logical_and(live, k_lo <= q_hi)
    if window is not None:
        # tile intersects the |q - k| < window band
        live = jnp.logical_and(live, k_hi > q_lo - window)
        if not causal:
            live = jnp.logical_and(live, k_lo < q_hi + window)
    return live


# ===========================================================================
# forward
# ===========================================================================

def _fwd_kernel(H, Bq, Bk, scale, causal, window, prec,
                qoff_ref, koff_ref, q_ref, k_ref, v_ref, kv_ref,
                o_ref, lse_ref, m_s, l_s, acc_s):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)
    # m/l live in the first lane of a [Bq, 128] scratch (TPU tiles are
    # 128-lane; a [Bq, 1] buffer would violate the minimum tile)

    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(_tile_live(q_off, k_off, iq, ik, Bq, Bk, causal, window))
    def _():
        q = q_ref[0].astype(jnp.float32)                 # [Bq, D]
        k = k_ref[0].astype(jnp.float32)                 # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec) * scale
        mask = _tile_mask(kv_ref[0, 0], q_off, k_off, iq, ik, Bq, Bk, causal,
                          window)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev, l_prev = m_s[:, :1], l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)                      # kill -inf rows
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v_ref[0].astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        acc_s[:] = acc_s[:] * corr + pv
        m_s[:, :1] = m_new
        l_s[:, :1] = l_new

    @pl.when(ik == nk - 1)
    def _():
        l = l_s[:, :1]
        o_ref[0] = jnp.where(l > 0, acc_s[:] / jnp.maximum(l, 1e-30),
                             0.0).astype(o_ref.dtype)
        # -inf for fully-masked rows: a ring combine weighs them out with
        # exp(lse - total) = 0, and the backward mask already zeroes p
        lse_ref[0, 0] = jnp.where(l[:, 0] > 0, m_s[:, 0] + jnp.log(l[:, 0]),
                                  -jnp.inf)


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _kv_index(H, H_kv):
    """Map the query-head grid index bh in [0, B*H) to its kv row in
    [0, B*H_kv) — grouped-query attention reads kv straight from the small
    [B*H_kv, Tk, D] array, never materializing the repeat in HBM."""
    rep = H // H_kv
    return lambda bh: (bh // H) * H_kv + (bh % H) // rep


def _in_kernel_precision(*arrays):
    """fp32 inputs get 3-pass (HIGHEST) in-kernel matmuls — the MXU's
    default single-bf16-pass fp32 visibly diverges from a true-fp32
    reference (measured on v5e: 0.02% of elements out at 2e-3, MEASURE/
    parity round 4); bf16 inputs keep the fast default, their tolerance
    already absorbs one bf16 rounding."""
    if any(a.dtype == jnp.float32 for a in arrays):
        return jax.lax.Precision.HIGHEST
    return None


def _fwd_call(q, k, v, kv_mask, q_off, k_off, H, scale, causal, window,
              Bq, Bk):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    H_kv = k.shape[0] // (BH // H)
    kvi = _kv_index(H, H_kv)
    nq, nk = Tq // Bq, Tk // Bk
    kernel = functools.partial(_fwd_kernel, H, Bq, Bk, scale, causal, window,
                               _in_kernel_precision(q, k, v))
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((1, Bq, D), lambda bh, iq, ik: (bh, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Bk, D), lambda bh, iq, ik: (kvi(bh), ik, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Bk, D), lambda bh, iq, ik: (kvi(bh), ik, 0),
                         memory_space=pltpu.VMEM),
            # 2-D arrays ride with a singleton middle dim: mosaic requires
            # the block's last-two dims be (8k, 128k) or equal the array's —
            # a (1, Bk) block on [B, Tk] has sublane dim 1 != B and is
            # rejected on hardware (interpret mode never checks)
            pl.BlockSpec((1, 1, Bk), lambda bh, iq, ik: (bh // H, 0, ik),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, Bq, D), lambda bh, iq, ik: (bh, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Bq), lambda bh, iq, ik: (bh, 0, iq),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Bq, 128), jnp.float32),   # running max (lane 0)
            pltpu.VMEM((Bq, 128), jnp.float32),   # running sum (lane 0)
            pltpu.VMEM((Bq, D), jnp.float32),     # output accumulator
        ],
        interpret=_interpret(),
    )(q_off, k_off, q, k, v, kv_mask)


# ===========================================================================
# backward
# ===========================================================================

def _bwd_dq_kernel(H, Bq, Bk, scale, causal, window, prec,
                   qoff_ref, koff_ref,
                   q_ref, k_ref, v_ref, kv_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_s):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        dq_s[:] = jnp.zeros_like(dq_s)

    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(_tile_live(q_off, k_off, iq, ik, Bq, Bk, causal, window))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec) * scale
        mask = _tile_mask(kv_ref[0, 0], q_off, k_off, iq, ik, Bq, Bk, causal,
                          window)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0][:, None]), 0.0)  # [Bq, Bk]

        do = do_ref[0].astype(jnp.float32)                          # [Bq, D]
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_s[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32,
                                       precision=prec)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(H, nq, Bq, Bk, scale, causal, window, prec,
                    qoff_ref, koff_ref,
                    q_ref, k_ref, v_ref, kv_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s):
    # grid (B*H_kv, nk, rep*nq): the sequential inner axis walks every
    # (query head of the group) x (q tile) pair, so one program owns each
    # dk/dv block and grouped-query heads accumulate without HBM expansion
    ik, inner = pl.program_id(1), pl.program_id(2)
    n_inner = pl.num_programs(2)
    iq = inner % nq

    @pl.when(inner == 0)
    def _():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(_tile_live(q_off, k_off, iq, ik, Bq, Bk, causal, window))
    def _():
        q = q_ref[0].astype(jnp.float32)                          # [Bq, D]
        k = k_ref[0].astype(jnp.float32)                          # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec) * scale
        mask = _tile_mask(kv_ref[0, 0], q_off, k_off, iq, ik, Bq, Bk, causal,
                          window)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0][:, None]), 0.0)  # [Bq, Bk]

        do = do_ref[0].astype(jnp.float32)                          # [Bq, D]
        # dv += p^T @ do
        dv_s[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32,
                                       precision=prec)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        # dk += ds^T @ q
        dk_s[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32,
                                       precision=prec)

    @pl.when(inner == n_inner - 1)
    def _():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, kv_mask, q_off, k_off, o, lse, do, dlse,
              H, scale, causal, window, Bq, Bk):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    BHkv = k.shape[0]
    H_kv = BHkv // (BH // H)
    rep = H // H_kv
    kvi = _kv_index(H, H_kv)
    nq, nk = Tq // Bq, Tk // Bk
    # d lse/ds_j = p_j, so the lse cotangent folds into the delta term:
    # ds = p (dp - delta + dlse) = p (dp - (delta - dlse))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :] - dlse                 # [BH, 1, Tq]
    delta = jnp.where(jnp.isfinite(delta), delta, 0.0)

    q_spec = pl.BlockSpec((1, Bq, D), lambda bh, iq, ik: (bh, iq, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, Bk, D), lambda bh, iq, ik: (kvi(bh), ik, 0),
                           memory_space=pltpu.VMEM)
    kmask_spec = pl.BlockSpec((1, 1, Bk), lambda bh, iq, ik: (bh // H, 0, ik),
                              memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, Bq), lambda bh, iq, ik: (bh, 0, iq),
                            memory_space=pltpu.VMEM)

    prec = _in_kernel_precision(q, k, v)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, H, Bq, Bk, scale, causal, window,
                          prec),
        grid=(BH, nq, nk),
        in_specs=[_scalar_spec(), _scalar_spec(),
                  q_spec, kv_spec, kv_spec, kmask_spec, q_spec,
                  row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((BH, Tq, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((Bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q_off, k_off, q, k, v, kv_mask, do, lse, delta)[0]

    # swapped grid: k tiles outer; the inner axis walks (group head, q tile)
    # pairs so grouped kv heads accumulate their whole group sequentially
    def bh_of(bhkv, inner):
        return (bhkv // H_kv) * H + (bhkv % H_kv) * rep + inner // nq

    q_spec2 = pl.BlockSpec(
        (1, Bq, D), lambda bhkv, ik, inner: (bh_of(bhkv, inner), inner % nq, 0),
        memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, Bk, D), lambda bhkv, ik, inner: (bhkv, ik, 0),
                            memory_space=pltpu.VMEM)
    kmask_spec2 = pl.BlockSpec(
        (1, 1, Bk), lambda bhkv, ik, inner: (bhkv // H_kv, 0, ik),
        memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec(
        (1, 1, Bq), lambda bhkv, ik, inner: (bh_of(bhkv, inner), 0, inner % nq),
        memory_space=pltpu.VMEM)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, H, nq, Bq, Bk, scale, causal,
                          window, prec),
        grid=(BHkv, nk, rep * nq),
        in_specs=[_scalar_spec(), _scalar_spec(),
                  q_spec2, kv_spec2, kv_spec2, kmask_spec2, q_spec2,
                  row_spec2, row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((BHkv, Tk, D), k.dtype),
                   jax.ShapeDtypeStruct((BHkv, Tk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((Bk, D), jnp.float32),
                        pltpu.VMEM((Bk, D), jnp.float32)],
        interpret=_interpret(),
    )(q_off, k_off, q, k, v, kv_mask, do, lse, delta)
    return dq, dk, dv


# ===========================================================================
# custom-vjp wrapper (padded, [BH, T, D] layout)
# ===========================================================================

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash(q, k, v, kv_mask, q_off, k_off, H, scale, causal, window, Bq, Bk):
    return _fwd_call(q, k, v, kv_mask, q_off, k_off, H, scale, causal,
                     window, Bq, Bk)


def _flash_fwd(q, k, v, kv_mask, q_off, k_off, H, scale, causal, window,
               Bq, Bk):
    o, lse = _fwd_call(q, k, v, kv_mask, q_off, k_off, H, scale, causal,
                       window, Bq, Bk)
    return (o, lse), (q, k, v, kv_mask, q_off, k_off, o, lse)


def _flash_bwd(H, scale, causal, window, Bq, Bk, res, cts):
    q, k, v, kv_mask, q_off, k_off, o, lse = res
    do, dlse = cts
    dq, dk, dv = _bwd_call(q, k, v, kv_mask, q_off, k_off, o, lse, do, dlse,
                           H, scale, causal, window, Bq, Bk)
    return dq, dk, dv, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: Array, k: Array, v: Array,
    q_valid: Optional[Array] = None,
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    q_offset: Union[int, Array] = 0,
    k_offset: Union[int, Array] = 0,
    return_lse: bool = False,
    window: Optional[int] = None,
):
    """Drop-in for `dot_product_attention`: q [B,Tq,H,D], k/v [B,Tk,H,D]
    -> [B,Tq,H,D], same masking semantics, fused pallas execution.

    With `return_lse`, also returns the per-row log-sum-exp [B, H, Tq]
    (fp32; -inf for fully-masked rows) so a context-parallel caller can
    combine per-shard results; q_offset/k_offset globalize the causal
    positions for such shard calls (scalars, may be traced)."""
    B, Tq, H, D = q.shape
    H_kv = k.shape[2]
    assert H % H_kv == 0, \
        f"num_heads {H} not divisible by num_kv_heads {H_kv}"
    Tk = k.shape[1]
    if scale is None:
        scale = D ** -0.5

    # low-precision (bf16/fp16) minimum TPU tile is (16, 128) vs fp32's
    # (8, 128): both the auto-sized tile for short sequences AND any
    # caller-chosen block must round up to the dtype's sublane minimum or
    # Mosaic rejects the block shapes
    from paddle_tpu.utils.dtypes import sublane_min
    sub = sublane_min(q, k, v)
    Bq = _round_up(min(block_q, _round_up(Tq, sub)), sub)
    Bk = _round_up(min(block_k, _round_up(Tk, sub)), sub)
    Tqp, Tkp = _round_up(Tq, Bq), _round_up(Tk, Bk)
    Dp = _round_up(D, 128)

    def to_bh(x, T, Tp):
        x = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0), (0, Dp - D)))
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], Tp, -1)

    qp = to_bh(q, Tq, Tqp)                   # [B*H, Tqp, Dp]
    kp = to_bh(k, Tk, Tkp)                   # [B*H_kv, Tkp, Dp] — kv stay
    vp = to_bh(v, Tk, Tkp)                   # at their grouped head count

    kv_mask = jnp.ones((B, Tk), jnp.float32) if k_valid is None \
        else k_valid.astype(jnp.float32)
    # singleton middle dim: see the mosaic block-rule note in _fwd_call
    kv_mask = jnp.pad(kv_mask, ((0, 0), (0, Tkp - Tk)))[:, None, :]

    q_off = jnp.asarray(q_offset, jnp.int32).reshape(1)
    k_off = jnp.asarray(k_offset, jnp.int32).reshape(1)
    o, lse = _flash(qp, kp, vp, kv_mask, q_off, k_off,
                    H, float(scale), bool(causal),
                    None if window is None else int(window), Bq, Bk)
    o = o.reshape(B, H, Tqp, Dp).transpose(0, 2, 1, 3)[:, :Tq, :, :D]
    if q_valid is not None:
        # invalid query rows output exactly 0; the zeroed cotangent also
        # kills their dk/dv contributions in the backward kernels
        o = o * q_valid[:, :, None, None].astype(o.dtype)
    if not return_lse:
        return o
    lse = lse.reshape(B, H, Tqp)[:, :, :Tq]
    if q_valid is not None:
        lse = jnp.where(q_valid[:, None, :], lse, -jnp.inf)
    return o, lse
