"""CTC loss via the standard alpha recursion on the extended label sequence.

TPU re-design of the reference's CTC (ref: paddle/gserver/layers/
{CTCLayer,LinearChainCTC}.cpp): batched, masked `lax.scan` over time in log
space; autodiff provides the gradient the reference derives by the beta
recursion.  Works on padded [B, T, C] probability inputs (the layer below
applies softmax, matching the reference's usage).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array
_NEG = -1e30


def ctc_loss(
    probs: Array,        # [B, T, C] probabilities (softmax output)
    input_lengths: Array,  # [B]
    labels: Array,       # [B, L] int labels (padded)
    label_lengths: Array,  # [B]
    blank: int = 0,
    norm_by_times: bool = False,
) -> Array:
    """Per-sequence -log p(labels | probs)."""
    logp = jnp.log(jnp.maximum(probs, 1e-10))
    B, T, C = logp.shape
    L = labels.shape[1]
    S = 2 * L + 1

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(S)[None, :] < (2 * label_lengths + 1)[:, None]

    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate([jnp.full((B, 2), -1, labels.dtype), ext[:, :-2]], axis=1)
    can_skip = (jnp.arange(S)[None, :] % 2 == 1) & (ext != ext_prev2)

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)      # [B, S]

    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0, emit(0)[:, 1], _NEG))

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        new = merged + emit(t)
        new = jnp.where(ext_valid, new, _NEG)
        valid_t = (t < input_lengths)[:, None]
        return jnp.where(valid_t, new, alpha), None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # answer: logaddexp of positions 2*len-1 (last label) and 2*len (last blank)
    s_last = 2 * label_lengths
    a_last_blank = jnp.take_along_axis(alpha, s_last[:, None], axis=1)[:, 0]
    a_last_lbl = jnp.take_along_axis(
        alpha, jnp.maximum(s_last - 1, 0)[:, None], axis=1)[:, 0]
    a_last_lbl = jnp.where(label_lengths > 0, a_last_lbl, _NEG)
    ll = jnp.logaddexp(a_last_blank, a_last_lbl)
    cost = -ll
    if norm_by_times:
        cost = cost / jnp.maximum(input_lengths.astype(cost.dtype), 1.0)
    return cost
