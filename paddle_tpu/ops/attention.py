"""Scaled-dot-product attention ops: dense, blockwise (online-softmax), and
ring attention for sequence/context parallelism.

This is a NEW capability beyond the reference (which predates transformer
attention — its closest analog is the additive-attention composite
`simple_attention`, ref: python/paddle/trainer_config_helpers/networks.py:1257,
and the zero-padding sequence machinery of SURVEY.md §5 "long-context").
The TPU framework makes long-context first-class:

  * `dot_product_attention` — one fused XLA einsum-softmax-einsum; masking by
    per-sequence lengths and/or causality.
  * `blockwise_attention` — O(T) memory online-softmax accumulation over
    key/value blocks (the flash-attention recurrence), written with
    `lax.scan` so XLA keeps the running (m, l, o) accumulators in registers
    /VMEM instead of materializing the [T, T] score matrix.
  * `ring_attention` — context parallelism over a mesh axis: each device
    holds a sequence shard; key/value shards rotate around the ring via
    `lax.ppermute` while every device folds each incoming block into its
    online-softmax accumulator.  One step of compute overlaps with the next
    ppermute.  Equivalent math to the single-device versions, differentiable
    end-to-end (ppermute has a transpose rule, so jax.grad produces the
    reverse ring automatically).

Layouts follow TPU conventions: q/k/v are [B, T, H, Dh] (batch, time, heads,
head_dim); scores are [B, H, Tq, Tk] so the contractions are MXU-friendly
einsums.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

_NEG_INF = -1e30


def _score_mask(
    q_pos: Array,            # [Tq] global positions of the query rows
    k_pos: Array,            # [Tk] global positions of the key rows
    q_valid: Optional[Array],   # [B, Tq] or None
    k_valid: Optional[Array],   # [B, Tk] or None
    causal: bool,
    window: Optional[int] = None,
) -> Optional[Array]:
    """Combined validity mask broadcastable to [B, 1, Tq, Tk]; None = all valid.

    `window` keeps only keys with |q_pos - k_pos| < window (sliding-window /
    local attention; one-sided when combined with causal)."""
    mask = None
    if causal:
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]    # [1,1,Tq,Tk]
    if window is not None:
        d = q_pos[:, None] - k_pos[None, :]
        w = (jnp.abs(d) < window)[None, None]
        mask = w if mask is None else jnp.logical_and(mask, w)
    if k_valid is not None:
        kv = k_valid[:, None, None, :]                           # [B,1,1,Tk]
        mask = kv if mask is None else jnp.logical_and(mask, kv)
    if q_valid is not None:
        qv = q_valid[:, None, :, None]                           # [B,1,Tq,1]
        mask = qv if mask is None else jnp.logical_and(mask, qv)
    return mask


def rope(x: Array, positions: Array, theta: float = 10000.0) -> Array:
    """Rotary position embedding (RoPE, Su et al. 2021) — NEW capability
    beyond the reference.  x [B, T, H, D] with D even, positions [T] (or
    [B, T]) absolute token positions; rotate-half convention (feature i
    pairs with i + D/2, the GPT-NeoX/llama layout — NOT the interleaved
    consecutive-pair GPT-J layout) with position-dependent angles, so q·k
    depends only on relative offsets.
    Applied to q/k BEFORE attention, it composes with every implementation
    (dense/blockwise/flash/ring) — for ring/context-parallel shards pass the
    shard's global positions."""
    D = x.shape[-1]
    assert D % 2 == 0, f"rope needs an even head dim, got {D}"
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions[..., None].astype(jnp.float32) * freqs     # [..., T, half]
    if ang.ndim == 2:                                          # [T, half]
        ang = ang[None]                                        # [1, T, half]
    cos = jnp.cos(ang)[:, :, None, :]                          # [B|1, T, 1, half]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.astype(x.dtype)


def _expand_kv_heads(k: Array, v: Array, num_heads: int):
    """Grouped-query attention: k/v carry H_kv <= H heads; repeat each kv
    head over its query-head group so every impl sees matching heads."""
    h_kv = k.shape[2]
    if h_kv == num_heads:
        return k, v
    assert num_heads % h_kv == 0, \
        f"num_heads {num_heads} not divisible by num_kv_heads {h_kv}"
    rep = num_heads // h_kv
    return (jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2))


def dot_product_attention(
    q: Array, k: Array, v: Array,
    q_valid: Optional[Array] = None,
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> Array:
    """Dense reference attention. q [B,Tq,H,D], k/v [B,Tk,H_kv,D] (H_kv may
    divide H for grouped-query attention) -> [B,Tq,H,D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    k, v = _expand_kv_heads(k, v, q.shape[2])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = _score_mask(jnp.arange(q.shape[1]), jnp.arange(k.shape[1]),
                       q_valid, k_valid, causal, window)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        # rows with no valid key (fully masked) must output exactly 0
        any_valid = jnp.any(mask, axis=-1, keepdims=True)
        p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _online_block(
    acc: tuple[Array, Array, Array],
    q: Array, k_blk: Array, v_blk: Array,
    q_pos: Array, k_pos: Array,
    q_valid: Optional[Array], k_valid_blk: Optional[Array],
    causal: bool, scale: float,
    window: Optional[int] = None,
) -> tuple[Array, Array, Array]:
    """Fold one key/value block into the online-softmax accumulator.

    acc = (o [B,Tq,H,D] f32, m [B,H,Tq] running max, l [B,H,Tq] running sum).
    """
    o, m, l = acc
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale       # [B,H,Tq,Tk]
    mask = _score_mask(q_pos, k_pos, q_valid, k_valid_blk, causal, window)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)                            # kill -inf rows
    corr = jnp.exp(m - m_new)                                  # [B,H,Tq]
    l_new = corr * l + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(p.dtype))
    o_new = o * jnp.moveaxis(corr, 1, 2)[..., None] + pv
    return o_new, m_new, l_new


def _finalize(o: Array, l: Array, dtype) -> Array:
    """o / l with fully-masked rows (l == 0) -> 0."""
    denom = jnp.moveaxis(l, 1, 2)[..., None]                   # [B,Tq,H,1]
    return jnp.where(denom > 0, o / jnp.maximum(denom, 1e-30), 0.0).astype(dtype)


def _init_acc(B: int, Tq: int, H: int, D: int) -> tuple[Array, Array, Array]:
    return (jnp.zeros((B, Tq, H, D), jnp.float32),
            jnp.full((B, H, Tq), _NEG_INF, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32))


def blockwise_attention(
    q: Array, k: Array, v: Array,
    q_valid: Optional[Array] = None,
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_k: int = 512,
    window: Optional[int] = None,
) -> Array:
    """Online-softmax attention over key blocks — O(Tq * block_k) score memory.

    Same math as `dot_product_attention` (incl. grouped kv heads and sliding
    window); the scan carry holds (o, m, l) so the full [Tq, Tk] score
    matrix never exists.
    """
    B, Tq, H, D = q.shape
    k, v = _expand_kv_heads(k, v, H)
    Tk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    block_k = min(block_k, Tk)
    n_blocks = -(-Tk // block_k)
    pad = n_blocks * block_k - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        kv_pad = (jnp.arange(n_blocks * block_k) < Tk)[None, :]
        k_valid = kv_pad if k_valid is None else \
            jnp.logical_and(jnp.pad(k_valid, ((0, 0), (0, pad))), kv_pad)
    q_pos = jnp.arange(Tq)
    kb = jnp.moveaxis(k.reshape(B, n_blocks, block_k, H, D), 1, 0)
    vb = jnp.moveaxis(v.reshape(B, n_blocks, block_k, H, D), 1, 0)
    kvalb = (None if k_valid is None else
             jnp.moveaxis(jnp.broadcast_to(
                 k_valid, (B, n_blocks * block_k)).reshape(B, n_blocks, block_k), 1, 0))

    # remat: without it, scan's backward saves every block's score tile —
    # n_blocks x [B, H, Tq, block_k] fp32 residuals, measured 32 GB at
    # T=16384 on v5e (MEASURE/attn_bench round 4) where the whole point of
    # blockwise is O(T) memory.  Recomputing the tile in backward is the
    # standard flash-attention trade and keeps train-mode long context
    # viable on the portable (non-pallas) path too.
    # prevent_cse=False: CSE prevention is unnecessary for a scan body
    # (the scan barrier already keeps fwd/bwd apart) and only blocks XLA
    # optimizations
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def body(acc, xs):
        i = xs["i"]
        k_pos = i * block_k + jnp.arange(block_k)
        acc = _online_block(acc, q, xs["k"], xs["v"], q_pos, k_pos,
                            q_valid, xs.get("kv"), causal, scale, window)
        return acc, None

    xs = {"i": jnp.arange(n_blocks), "k": kb, "v": vb}
    if kvalb is not None:
        xs["kv"] = kvalb
    (o, m, l), _ = lax.scan(body, _init_acc(B, Tq, H, D), xs)
    return _finalize(o, l, q.dtype)


def ring_attention(
    q: Array, k: Array, v: Array,
    axis_name: str,
    q_valid: Optional[Array] = None,
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    window: Optional[int] = None,
) -> Array:
    """Context-parallel attention for use INSIDE `shard_map` over `axis_name`.

    Every device holds its local sequence shard q/k/v [B, T_local, H, D]
    (shard d covers global positions [d*T_local, (d+1)*T_local)).  K/V shards
    rotate one hop per step via `lax.ppermute` while each device folds the
    incoming block into its online-softmax accumulator; after axis_size steps
    every query row has attended to every key.  The python loop is unrolled
    (axis_size is static) so XLA can overlap each ppermute with the previous
    block's einsums — the collective rides ICI behind the MXU work.

    On TPU each per-hop block runs the fused pallas flash kernel
    (ring flash attention): the kernel returns the block's normalized output
    + log-sum-exp, and blocks combine with exp(lse_b - m) weights — the same
    online-softmax math, score tiles never leaving VMEM.  `use_flash=False`
    forces the portable jnp fold (and is the oracle in tests).
    """
    B, Tl, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    from paddle_tpu.utils.jax_compat import axis_size
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_flash is None:
        from paddle_tpu.ops import pallas_attention
        use_flash = pallas_attention.supported()

    if use_flash:
        return _ring_flash(q, k, v, axis_name, idx, n, perm,
                           q_valid, k_valid, causal, scale, window)

    q_pos = idx * Tl + jnp.arange(Tl)
    acc = _init_acc(B, Tl, H, D)
    k_blk, v_blk, kv_blk = k, v, k_valid
    for step in range(n):
        src = (idx - step) % n                      # owner of the current block
        k_pos = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
        # grouped kv heads expand AFTER the rotation, so the ring moves the
        # small H_kv tensors over ICI
        k_use, v_use = _expand_kv_heads(k_blk, v_blk, H)
        acc = _online_block(acc, q, k_use, v_use, q_pos, k_pos,
                            q_valid, kv_blk, causal, scale, window)
        if step + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            if kv_blk is not None:
                kv_blk = lax.ppermute(kv_blk, axis_name, perm)
    o, m, l = acc
    return _finalize(o, l, q.dtype)


def _ring_flash(q, k, v, axis_name, idx, n, perm,
                q_valid, k_valid, causal, scale, window=None):
    """Ring attention with the pallas flash kernel per hop: each block call
    yields (o_b normalized, lse_b); blocks fold into a running
    (num, den, max) — o = num/den at the end.  Differentiable end-to-end
    (the kernel's custom VJP accepts the lse cotangent; ppermute has a
    transpose rule, so jax.grad produces the reverse ring automatically)."""
    from paddle_tpu.ops.pallas_attention import flash_attention

    B, Tl, H, D = q.shape
    m_run = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    num = jnp.zeros((B, Tl, H, D), jnp.float32)
    den = jnp.zeros((B, H, Tl), jnp.float32)

    k_blk, v_blk, kv_blk = k, v, k_valid
    for step in range(n):
        src = (idx - step) % n                      # owner of the current block
        o_b, lse_b = flash_attention(
            q, k_blk, v_blk, q_valid=q_valid, k_valid=kv_blk, causal=causal,
            scale=scale, q_offset=idx * Tl, k_offset=src * k_blk.shape[1],
            return_lse=True, window=window)
        m_new = jnp.maximum(m_run, lse_b)
        alive = m_new > -jnp.inf
        # sanitize BEFORE exp: -inf - -inf would be NaN, and a NaN in the
        # untaken where-branch still poisons gradients (0 * NaN)
        m_safe = jnp.where(alive, m_new, 0.0)
        corr = jnp.where(alive & (m_run > -jnp.inf),
                         jnp.exp(jnp.where(m_run > -jnp.inf, m_run, 0.0)
                                 - m_safe), 0.0)
        w = jnp.where(alive & (lse_b > -jnp.inf),
                      jnp.exp(jnp.where(lse_b > -jnp.inf, lse_b, 0.0)
                              - m_safe), 0.0)
        num = num * jnp.moveaxis(corr, 1, 2)[..., None] \
            + o_b.astype(jnp.float32) * jnp.moveaxis(w, 1, 2)[..., None]
        den = den * corr + w
        m_run = m_new
        if step + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            if kv_blk is not None:
                kv_blk = lax.ppermute(kv_blk, axis_name, perm)
    return _finalize(num, den, q.dtype)


def multi_head_attention(
    query: Array,                     # [B, Tq, Dq]
    key: Array,                       # [B, Tk, Dk]
    value: Array,                     # [B, Tk, Dv]
    w_q: Array, w_k: Array, w_v: Array, w_o: Array,
    num_heads: int,
    q_valid: Optional[Array] = None,
    k_valid: Optional[Array] = None,
    causal: bool = False,
    bias_o: Optional[Array] = None,
    attn_fn=dot_product_attention,
    num_kv_heads: Optional[int] = None,
    window: Optional[int] = None,
    use_rope: bool = False,
    rope_theta: float = 10000.0,
) -> Array:
    """Projected multi-head attention; attn_fn pluggable (dense / blockwise /
    flash / a ring closure from parallel/context.py).

    num_kv_heads < num_heads gives grouped-query attention (w_k/w_v project
    to num_kv_heads * head_dim); window gives sliding-window attention;
    use_rope applies rotary position embeddings to q/k."""
    B, Tq, _ = query.shape
    Tk = key.shape[1]
    model_dim = w_q.shape[1]
    Dh = model_dim // num_heads
    h_kv = num_kv_heads or num_heads
    q = (query @ w_q).reshape(B, Tq, num_heads, Dh)
    k = (key @ w_k).reshape(B, Tk, h_kv, Dh)
    v = (value @ w_v).reshape(B, Tk, h_kv, Dh)
    if use_rope:
        q = rope(q, jnp.arange(Tq), rope_theta)
        k = rope(k, jnp.arange(Tk), rope_theta)
    kw = {} if window is None else {"window": window}
    o = attn_fn(q, k, v, q_valid=q_valid, k_valid=k_valid, causal=causal,
                **kw)
    out = o.reshape(B, Tq, model_dim) @ w_o
    if bias_o is not None:
        out = out + bias_o
    return out


def cached_attention_step(
    q_new: Array,          # [B, Tn, H, D] new-token queries
    k_new: Array,          # [B, Tn, H_kv, D]
    v_new: Array,          # [B, Tn, H_kv, D]
    cache_k: Array,        # [B, Tmax, H_kv, D]
    cache_v: Array,        # [B, Tmax, H_kv, D]
    pos: Array,            # [B] int32 — tokens already resident per row
    n_new: Array,          # [B] int32 — valid new tokens this call (<= Tn)
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> tuple[Array, Array, Array, Array]:
    """Incremental causal attention against a fixed-size KV cache — the
    O(T)-per-token decode step (the reference's closest analog is the
    recurrent generator's carried state, RecurrentGradientMachine
    generation; transformers have no recurrence, so the cache IS the
    carried state).

    Row b's new tokens land at cache positions pos[b]..pos[b]+Tn-1 (rows
    advance independently — prompts have ragged lengths).  Writes use a
    one-hot batched matmul rather than per-row dynamic slices: static
    shapes, MXU-friendly, and scan/jit-stable.  Slots past pos+n_new hold
    garbage from padded prefill calls; causality (k_pos <= q_pos) already
    excludes them for every valid query, and the next call overwrites
    them.  Returns (out [B,Tn,H,D], new_cache_k, new_cache_v, new_pos).
    """
    B, Tn, H, D = q_new.shape
    Tmax = cache_k.shape[1]
    if scale is None:
        scale = D ** -0.5
    t = jnp.arange(Tmax)
    i = jnp.arange(Tn)
    # [B, Tmax, Tn] one-hot: slot t receives new token i of row b
    sel = (t[None, :, None] ==
           (pos[:, None, None] + i[None, None, :])).astype(cache_k.dtype)
    keep = 1.0 - jnp.max(sel, axis=2)                       # [B, Tmax]

    def scatter(cache, new):
        upd = jnp.einsum("bti,bihd->bthd", sel, new.astype(cache.dtype))
        return cache * keep[:, :, None, None] + upd

    ck, cv = scatter(cache_k, k_new), scatter(cache_v, v_new)

    qpos = pos[:, None] + i[None, :]                        # [B, Tn] global
    mask = t[None, None, :] <= qpos[:, :, None]             # causal, global
    if window is not None:
        mask = jnp.logical_and(mask,
                               t[None, None, :] > qpos[:, :, None] - window)

    k_full, v_full = _expand_kv_heads(ck, cv, H)
    s = jnp.einsum("bqhd,bkhd->bhqk", q_new, k_full) * scale
    from paddle_tpu.utils.dtypes import promote_compute
    s = promote_compute(s)
    s = jnp.where(mask[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_full.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
    return out, ck, cv, pos + n_new


def _tp_shards(mesh) -> int:
    """Size of a mesh's `model` axis (1 = no tensor parallelism); reads
    the mesh's own shape map — no parallel.mesh import, keeping this
    module cycle-free."""
    if mesh is None:
        return 1
    try:
        return int(dict(mesh.shape).get("model", 1))
    except (AttributeError, TypeError):
        return 1


def _tp_paged_call(mesh, body, head_args, pool_args, repl_args,
                   head_axis: int):
    """Run a paged-attention body under shard_map over the mesh `model`
    axis: query/key/value shard on their head axis, the page pools on
    their kv-head axis (axis 2), tables/positions replicate.  Each device
    reads and writes ONLY its own head shard of the pools — the pools are
    never all-gathered (tools/hlo_shard_check.py asserts it on the
    lowered HLO), and since no reduction ever crosses heads inside
    attention, the sharded math is the single-device math per head.
    Returns (out [head-sharded], k_pages', v_pages' [pool-sharded])."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.utils.jax_compat import shard_map

    head = P(*([None] * head_axis + ["model", None]))
    pool = P(None, None, "model", None)
    in_specs = tuple([head] * len(head_args) + [pool] * len(pool_args)
                     + [P()] * len(repl_args))
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=(head, pool, pool), check_vma=False)
    return fn(*head_args, *pool_args, *repl_args)


def paged_attention_step(
    q_new: Array,          # [S, 1, H, D] one new-token query per slot
    k_new: Array,          # [S, 1, H_kv, D]
    v_new: Array,          # [S, 1, H_kv, D]
    k_pages: Array,        # [P, page_size, H_kv, D] shared page pool
    v_pages: Array,        # [P, page_size, H_kv, D]
    page_table: Array,     # [S, max_pages] int32 physical page per logical
                           # page of each slot (0 = unmapped -> trash page)
    pos: Array,            # [S] int32 tokens already resident per slot
    scale: Optional[float] = None,
    window: Optional[int] = None,
    use_kernel: Optional[bool] = None,
    mesh=None,
) -> tuple[Array, Array, Array]:
    """One continuous-batching decode micro-step against a PAGED KV cache —
    the serving analog of `cached_attention_step`: instead of one dense
    [B, Tmax, H_kv, D] cache per request batch, every slot's context lives
    in fixed-size pages of a shared pool, mapped by a per-slot page table,
    so cache HBM is proportional to tokens actually held and ONE compiled
    step serves an ever-changing request mix.

    Contract (mirrors cached_attention_step with Tn == 1): slot s's new
    token lands at logical position pos[s] — physical page
    page_table[s, pos[s] // page_size], offset pos[s] % page_size — and
    attends causally over logical positions 0..pos[s].  Physical page 0 is
    the TRASH page: unmapped logical pages (inactive slots, a paused slot
    whose next page is not yet allocated) write there and their reads are
    causally masked or discarded by the scheduler, so the one compiled
    program needs no per-slot branching.  Gathered positions past pos[s]
    carry finite garbage; the -1e30 mask makes their softmax weight exactly
    0.0, so they cannot perturb live slots (same discipline as the dense
    cache's padded-prefill slots).

    Returns (out [S, 1, H, D], new_k_pages, new_v_pages).  `use_kernel`
    routes the read through the Pallas ragged-paged kernel
    (ops/pallas_paged.py) — default: auto (kernel when supported and no
    sliding window); False forces the jnp gather fallback (the oracle in
    tests and the exactness anchor of the serving engine).

    SCAN-BODY SAFE: the write+read core is pure in its operands (no
    host callback, no per-call state — including the shard_map TP path,
    whose collective set is fixed per call), so the engine's multi-step
    decode (`decode_steps=k`) may trace it inside a `lax.scan` body
    with `pos`/`page_table`-addressed writes riding the scan carry —
    body i+1 reads exactly the pool state body i's scatter produced,
    and the body appears ONCE in the lowered HLO
    (tools/hlo_shard_check.py's "scan" step is the proof).
    """
    S, Tn, H, D = q_new.shape
    assert Tn == 1, "paged decode feeds exactly one new token per slot"
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    if scale is None:
        scale = D ** -0.5

    if _tp_shards(mesh) > 1:
        # tensor-parallel decode: heads partition over the mesh `model`
        # axis — the whole write+read core runs per head shard under
        # shard_map (each device's local H/h_kv keep the same grouped-
        # query ratio; the engine validated divisibility)
        def body(q, k, v, kp, vp, tbl, p):
            return paged_attention_step(q, k, v, kp, vp, tbl, p,
                                        scale=scale, window=window,
                                        use_kernel=use_kernel, mesh=None)

        return _tp_paged_call(mesh, body, (q_new, k_new, v_new),
                              (k_pages, v_pages), (page_table, pos),
                              head_axis=2)

    # -- write: scatter each slot's new k/v into its current page --------
    phys = jnp.take_along_axis(page_table, (pos // page_size)[:, None],
                               axis=1)[:, 0]                     # [S]
    off = pos % page_size
    ck = k_pages.at[phys, off].set(k_new[:, 0].astype(k_pages.dtype))
    cv = v_pages.at[phys, off].set(v_new[:, 0].astype(v_pages.dtype))

    if use_kernel is None:
        from paddle_tpu.ops import pallas_paged
        use_kernel = pallas_paged.supported() and window is None
    if use_kernel:
        if window is not None:
            raise ValueError(
                "paged_attention_step: the Pallas ragged-paged kernel has "
                "no sliding-window support — pass use_kernel=False (or "
                "None for auto, which already falls back) for window "
                "attention")
        from paddle_tpu.ops import pallas_paged
        out = pallas_paged.paged_attention(q_new[:, 0], ck, cv, page_table,
                                           pos + 1, scale=scale)[:, None]
        return out, ck, cv

    # -- read: page-table gather -> [S, T_ctx] contiguous view -----------
    T_ctx = max_pages * page_size
    kc = ck[page_table].reshape(S, T_ctx, *ck.shape[2:])
    vc = cv[page_table].reshape(S, T_ctx, *cv.shape[2:])
    k_full, v_full = _expand_kv_heads(kc, vc, H)
    t = jnp.arange(T_ctx)
    mask = t[None, None, :] <= pos[:, None, None]                # causal
    if window is not None:
        mask = jnp.logical_and(mask,
                               t[None, None, :] > pos[:, None, None] - window)
    s = jnp.einsum("bqhd,bkhd->bhqk", q_new, k_full) * scale
    from paddle_tpu.utils.dtypes import promote_compute
    s = promote_compute(s)
    s = jnp.where(mask[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_full.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
    return out, ck, cv


def ragged_paged_attention_step(
    q_new: Array,          # [T, H, D] packed query rows — ONE token each
    k_new: Array,          # [T, H_kv, D]
    v_new: Array,          # [T, H_kv, D]
    k_pages: Array,        # [P, page_size, H_kv, D] shared page pool
    v_pages: Array,        # [P, page_size, H_kv, D]
    page_table: Array,     # [S, max_pages] int32 physical page per logical
                           # page of each table row (0 = unmapped -> trash)
    row_slot: Array,       # [T] int32 page-table row each query row reads
    row_pos: Array,        # [T] int32 global position of each query row
    scale: Optional[float] = None,
    window: Optional[int] = None,
    use_kernel: Optional[bool] = None,
    mesh=None,
) -> tuple[Array, Array, Array]:
    """RAGGED paged attention — the mixed prefill/decode step of the
    serving engine (the full Ragged Paged Attention shape of
    arXiv:2604.15464, generalizing `paged_attention_step`'s one-token-per-
    slot contract): query tokens are PACKED into a flat [T] row dimension
    where row r is one token of slot `row_slot[r]` at global position
    `row_pos[r]`.  A decode slot contributes one row; a prompt being
    chunk-prefilled contributes up to `chunk` consecutive rows — both
    shapes share this ONE dispatch, so a long cold prompt can no longer
    stall every decoding slot's inter-token latency behind its own
    prefill program.

    Contract per row r: its k/v land at logical position row_pos[r] of
    table row row_slot[r] (physical page page_table[row_slot[r],
    row_pos[r] // page_size], offset row_pos[r] % page_size), and it
    attends causally over that slot's logical positions 0..row_pos[r].
    All writes scatter BEFORE the read, so chunk rows of the same slot
    see each other's K/V under the causal mask (token i of a chunk
    attends tokens 0..i — exactly the dense prefill semantics).  Padding
    rows point `row_slot` at an all-zero table row (every logical page
    unmapped -> trash page 0) with row_pos 0: their writes land in the
    trash page and their outputs are garbage the scheduler discards.

    The SPECULATIVE verify step (serving/engine.py `_spec_impl`) rides
    this same contract with a third row flavor: a decoding slot's
    draft CHAIN — its committed last token at row_pos = pos plus k
    drafted tokens at pos+1..pos+k — so draft row i attends the
    committed context plus drafts 1..i-1, exactly the context a
    sequential engine would have if the drafts were true.  The scatter
    is ROLLBACK-SAFE by construction: a rejected draft's K/V sits at
    positions beyond the slot's committed length, where the causal
    mask excludes it from every live query, and the next step's rows
    overwrite those positions before the slot's pos can ever reach
    them — so the device state needs no undo, and the host merely
    returns the unjustified tail pages (paged_kv.uncommit_tail).

    Returns (out [T, H, D], new_k_pages, new_v_pages).  `use_kernel`
    routes the read through the Pallas ragged-paged kernel with the
    row->slot indirection (ops/pallas_paged.py); the jnp gather fallback
    is the exactness oracle (and the sliding-window path)."""
    T, H, D = q_new.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    if scale is None:
        scale = D ** -0.5

    if _tp_shards(mesh) > 1:
        # mixed prefill/decode under tensor parallelism: same head-shard
        # partition as the decode step, row indirection replicated
        def body(q, k, v, kp, vp, tbl, rs, rp):
            return ragged_paged_attention_step(q, k, v, kp, vp, tbl, rs,
                                               rp, scale=scale,
                                               window=window,
                                               use_kernel=use_kernel,
                                               mesh=None)

        return _tp_paged_call(mesh, body, (q_new, k_new, v_new),
                              (k_pages, v_pages),
                              (page_table, row_slot, row_pos), head_axis=1)

    # -- write: scatter every row's k/v into its slot's current page -----
    phys = page_table[row_slot, row_pos // page_size]             # [T]
    off = row_pos % page_size
    ck = k_pages.at[phys, off].set(k_new.astype(k_pages.dtype))
    cv = v_pages.at[phys, off].set(v_new.astype(v_pages.dtype))

    if use_kernel is None:
        from paddle_tpu.ops import pallas_paged
        use_kernel = pallas_paged.supported() and window is None
    if use_kernel:
        if window is not None:
            raise ValueError(
                "ragged_paged_attention_step: the Pallas ragged-paged "
                "kernel has no sliding-window support — pass "
                "use_kernel=False (or None for auto) for window attention")
        from paddle_tpu.ops import pallas_paged
        out = pallas_paged.paged_attention(q_new, ck, cv, page_table,
                                           row_pos + 1, scale=scale,
                                           row_slot=row_slot)
        return out, ck, cv

    # -- read: per-row page-table gather -> [T, T_ctx] contiguous view ---
    T_ctx = max_pages * page_size
    kc = ck[page_table[row_slot]].reshape(T, T_ctx, *ck.shape[2:])
    vc = cv[page_table[row_slot]].reshape(T, T_ctx, *cv.shape[2:])
    k_full, v_full = _expand_kv_heads(kc, vc, H)
    t = jnp.arange(T_ctx)
    mask = t[None, :] <= row_pos[:, None]                        # causal
    if window is not None:
        mask = jnp.logical_and(mask,
                               t[None, :] > row_pos[:, None] - window)
    s = jnp.einsum("qhd,qkhd->qhk", q_new, k_full) * scale
    from paddle_tpu.utils.dtypes import promote_compute
    s = promote_compute(s)
    s = jnp.where(mask[:, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_full.dtype)
    out = jnp.einsum("qhk,qkhd->qhd", p, v_full)
    return out, ck, cv


def additive_attention_step(
    dec_state: Array,      # [B, Ds] decoder state for THIS timestep
    w: Array,              # [Ds, D] state transform
    v: Array,              # [D] scoring vector
    enc_proj: Array,       # [B, T, D] pre-projected encoder states
    enc_seq: Array,        # [B, T, Dv] encoder values
    mask: Optional[Array] = None,   # [B, T] validity
) -> Array:
    """One Bahdanau additive-attention step, fused (ref: the reference's
    simple_attention 5-layer composite — networks.py:1257: fc + expand +
    addto/tanh + sequence-softmax + scaling + seq-pool).

    Single expression so XLA fuses score computation, masking, softmax and
    the context reduction into one pass over [B, T, D] instead of
    materializing each composite layer's [B, T, D] intermediate — inside
    the decoder scan this is the bandwidth-bound hot path (PERF.md: seq2seq
    gains need fewer bytes/step, not fewer flops).  Returns [B, Dv].
    """
    from paddle_tpu.utils.dtypes import promote_compute

    s = jnp.einsum("btd,d->bt",
                   jnp.tanh(enc_proj + (dec_state @ w)[:, None, :]), v)
    s = promote_compute(s)                      # fp32 softmax
    if mask is not None:
        s = jnp.where(mask, s, jnp.asarray(-1e30, s.dtype))
    alpha = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bt,btd->bd", alpha.astype(enc_seq.dtype), enc_seq)
