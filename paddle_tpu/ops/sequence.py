"""Variable-length sequence ops on padded [B, T, ...] tensors.

The reference keeps sequences as a flat (total_tokens x dim) matrix indexed by
sequenceStartPositions and re-buckets timesteps with SequenceToBatch so RNN
steps are dense GEMMs (ref: paddle/parameter/Argument.h:89-98,
paddle/gserver/layers/SequenceToBatch.h, paddle/cuda/src/hl_cuda_sequence.cu).
On TPU the idiomatic layout is *padded dense* [batch, max_len, dim] plus a
lengths vector: every op below is a masked dense computation that XLA tiles
onto the MXU/VPU, and `lax.scan` replaces the timestep re-bucketing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def length_mask(lengths: Array, max_len: int, dtype=jnp.bool_) -> Array:
    """[B] lengths -> [B, T] validity mask."""
    return (jnp.arange(max_len)[None, :] < lengths[:, None]).astype(dtype)


def seq_pool_max(x: Array, lengths: Array) -> Array:
    """Max over valid timesteps: [B,T,D],[B] -> [B,D]
    (ref: MaxLayer / hl_max_sequence_forward)."""
    mask = length_mask(lengths, x.shape[1])[..., None]
    neg = jnp.finfo(x.dtype).min
    return jnp.max(jnp.where(mask, x, neg), axis=1)


def seq_pool_avg(x: Array, lengths: Array, strategy: str = "average") -> Array:
    """Mean/sum/sqrt-n over valid timesteps (ref: AverageLayer,
    hl_sequence_avg_forward; average_strategy average|sum|squarerootn)."""
    mask = length_mask(lengths, x.shape[1], x.dtype)[..., None]
    total = jnp.sum(x * mask, axis=1)
    n = jnp.maximum(lengths.astype(x.dtype), 1.0)[:, None]
    if strategy == "sum":
        return total
    if strategy == "squarerootn":
        return total / jnp.sqrt(n)
    return total / n


def seq_pool_last(x: Array, lengths: Array) -> Array:
    """Last valid timestep: [B,T,D],[B] -> [B,D] (ref: SequenceLastInstanceLayer)."""
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


def seq_pool_first(x: Array, lengths: Array) -> Array:
    """First timestep (ref: SequenceLastInstanceLayer with select_first)."""
    return x[:, 0]


def nested_mask(lengths: Array, sub_lengths: Array, T: int,
                dtype=bool) -> Array:
    """Validity mask for a nested sequence [B, S, T]: position (b, s, t) is
    valid iff s < lengths[b] and t < sub_lengths[b, s]."""
    B, S = sub_lengths.shape
    s_valid = jnp.arange(S)[None, :] < lengths[:, None]               # [B,S]
    t_valid = jnp.arange(T)[None, None, :] < sub_lengths[:, :, None]  # [B,S,T]
    return (s_valid[:, :, None] & t_valid).astype(dtype)


def nested_pool_max(x: Array, lengths: Array, sub_lengths: Array) -> Array:
    """Max over all valid tokens of a nested sequence: [B,S,T,D] -> [B,D]."""
    mask = nested_mask(lengths, sub_lengths, x.shape[2])[..., None]
    neg = jnp.finfo(x.dtype).min
    return jnp.max(jnp.where(mask, x, neg), axis=(1, 2))


def nested_pool_avg(x: Array, lengths: Array, sub_lengths: Array,
                    strategy: str = "average") -> Array:
    """Mean/sum/sqrt-n over all valid tokens: [B,S,T,D] -> [B,D]."""
    mask = nested_mask(lengths, sub_lengths, x.shape[2], x.dtype)[..., None]
    total = jnp.sum(x * mask, axis=(1, 2))
    n = jnp.maximum(jnp.sum(mask, axis=(1, 2)), 1.0)
    if strategy == "sum":
        return total
    if strategy == "squarerootn":
        return total / jnp.sqrt(n)
    return total / n


def nested_pool_last(x: Array, lengths: Array, sub_lengths: Array) -> Array:
    """Last valid token overall: [B,S,T,D] -> [B,D] (ref:
    SequenceLastInstanceLayer on nested input).  Robust to empty
    subsequences anywhere in the valid region."""
    B, S, T = x.shape[:3]
    mask = nested_mask(lengths, sub_lengths, T).reshape(B, S * T)
    idx = (S * T - 1) - jnp.argmax(mask[:, ::-1], axis=1)  # 0-pad if none valid
    flat = x.reshape((B, S * T) + x.shape[3:])
    expand = idx.reshape((B, 1) + (1,) * (flat.ndim - 2))
    return jnp.take_along_axis(flat, expand, axis=1)[:, 0]


def nested_pool_first(x: Array, lengths: Array, sub_lengths: Array) -> Array:
    """First valid token overall: [B,S,T,D] -> [B,D].  Robust to empty
    subsequences anywhere in the valid region."""
    B, S, T = x.shape[:3]
    mask = nested_mask(lengths, sub_lengths, T).reshape(B, S * T)
    idx = jnp.argmax(mask, axis=1)
    flat = x.reshape((B, S * T) + x.shape[3:])
    expand = idx.reshape((B, 1) + (1,) * (flat.ndim - 2))
    return jnp.take_along_axis(flat, expand, axis=1)[:, 0]


def expand_to_sequence(x: Array, lengths: Array, max_len: int) -> Array:
    """Broadcast per-sequence vectors across timesteps: [B,D] -> [B,T,D],
    zeroed past each length (ref: ExpandLayer)."""
    mask = length_mask(lengths, max_len, x.dtype)[..., None]
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], max_len, x.shape[1])) * mask


def context_projection(
    x: Array,
    lengths: Array,
    context_start: int,
    context_length: int,
    padding: Optional[Array] = None,
) -> Array:
    """Concatenate a sliding window of timesteps per position:
    [B,T,D] -> [B,T,context_length*D]
    (ref: ContextProjection, hl_context_projection_forward).

    Out-of-range positions (before 0 / after length-1) read zeros, or rows of a
    trainable `padding` matrix [(up_pad+down_pad), D] when provided — matching
    the reference's trainable_padding.
    """
    B, T, D = x.shape
    mask = length_mask(lengths, T, x.dtype)[..., None]
    xm = x * mask
    cols = []
    up_pad = max(0, -context_start)
    for j in range(context_length):
        offset = context_start + j
        shifted = jnp.roll(xm, shift=-offset, axis=1)
        t = jnp.arange(T)[None, :]
        src = t + offset
        valid = (src >= 0) & (src < lengths[:, None])
        if padding is not None:
            if offset < 0:
                # positions src<0 read padding row (up_pad + src)
                pad_row = padding[jnp.clip(up_pad + src, 0, padding.shape[0] - 1)]
                fill = jnp.where((src < 0)[..., None], pad_row, 0.0)
            elif offset > 0:
                # positions src>=length read padding row (up_pad + (src - length))
                over = src - lengths[:, None]
                pad_row = padding[jnp.clip(up_pad + over, 0, padding.shape[0] - 1)]
                fill = jnp.where((over >= 0)[..., None], pad_row, 0.0)
            else:
                fill = jnp.zeros_like(shifted)
            col = jnp.where(valid[..., None], shifted, fill)
        else:
            col = jnp.where(valid[..., None], shifted, 0.0)
        cols.append(col)
    out = jnp.concatenate(cols, axis=-1)
    return out * mask


def seq_concat(a: Array, la: Array, b: Array, lb: Array) -> tuple[Array, Array]:
    """Concatenate two sequence batches along time: ([B,Ta,D],[B,Tb,D]) ->
    [B,Ta+Tb,D] with b's valid part starting right after a's
    (ref: SequenceConcatLayer)."""
    B, Ta, D = a.shape
    Tb = b.shape[1]
    T = Ta + Tb
    out_len = la + lb
    # scatter b at positions la..la+lb-1
    maska = length_mask(la, T, a.dtype)[..., None]
    padded_a = jnp.pad(a, ((0, 0), (0, Tb), (0, 0))) * maska
    t = jnp.arange(T)[None, :]
    src_b = t - la[:, None]
    valid_b = (src_b >= 0) & (src_b < lb[:, None])
    gathered_b = jnp.take_along_axis(
        jnp.pad(b, ((0, 0), (0, Ta), (0, 0))),
        jnp.clip(src_b, 0, T - 1)[..., None].repeat(D, axis=-1), axis=1)
    out = padded_a + jnp.where(valid_b[..., None], gathered_b, 0.0)
    return out, out_len


def seq_reshape(x: Array, lengths: Array, new_dim: int) -> tuple[Array, Array]:
    """Reshape each sequence's flat token stream to a new feature width
    (ref: SequenceReshapeLayer): [B,T,D] -> [B, T*D//new_dim, new_dim]."""
    B, T, D = x.shape
    assert (T * D) % new_dim == 0
    new_t = T * D // new_dim
    out = x.reshape(B, new_t, new_dim)
    new_len = lengths * D // new_dim
    return out, new_len


def seq_slice_first_tokens(x: Array, lengths: Array, n: int) -> tuple[Array, Array]:
    """First n tokens of each sequence (ref: SubSequenceLayer special case)."""
    return x[:, :n], jnp.minimum(lengths, n)


def sub_sequence(x: Array, offsets: Array, sizes: Array,
                 lengths: Array | None = None) -> tuple[Array, Array]:
    """Take a per-sequence slice [offset, offset+size) of each sequence
    (ref: gserver/layers/SubSequenceLayer.cpp:74-150 — inputs are the data
    sequence plus per-sequence offset and size id vectors).  Padded-dense
    re-design: a gather along time with an out-of-range mask.  The reference
    CHECK-aborts on out-of-bounds slices; under jit the slice is clamped to
    the valid range instead (size -> max(0, min(size, length - offset)))."""
    B, T = x.shape[0], x.shape[1]
    bound = lengths if lengths is not None else jnp.full_like(offsets, T)
    sizes = jnp.clip(jnp.minimum(sizes, bound - offsets), 0, T)
    t = jnp.arange(T)[None, :]
    src = offsets[:, None] + t
    valid = t < sizes[:, None]
    idx = jnp.where(valid, jnp.minimum(src, T - 1), 0)
    out = jnp.take_along_axis(x, idx.reshape(B, T, *([1] * (x.ndim - 2))), axis=1)
    out = jnp.where(valid.reshape(B, T, *([1] * (x.ndim - 2))), out, 0)
    return out, sizes.astype(jnp.int32)


def seq_reverse(x: Array, lengths: Array) -> Array:
    """Reverse each sequence's valid prefix in place: [B,T,D] -> [B,T,D]
    (used by reversed recurrent layers; ref: RecurrentLayer reversed_)."""
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]
    src = lengths[:, None] - 1 - t
    valid = src >= 0
    idx = jnp.where(valid, src, t)
    out = jnp.take_along_axis(x, idx.reshape(B, T, *([1] * (x.ndim - 2))), axis=1)
    return jnp.where(valid.reshape(B, T, *([1] * (x.ndim - 2))), out, x)


def _sub_valid(lengths: Array, sub_lengths: Array) -> Array:
    """[B, S] validity of each sub-sequence row: s < lengths[b] and the
    sub-sequence is non-empty."""
    S = sub_lengths.shape[1]
    return (jnp.arange(S)[None, :] < lengths[:, None]) & (sub_lengths > 0)


def nested_pool_max_per_sub(x: Array, lengths: Array,
                            sub_lengths: Array) -> Array:
    """Per-sub-sequence max: [B,S,T,D] -> [B,S,D] (the reference's
    AggregateLevel.EACH_SEQUENCE pooling); invalid/empty subs -> 0."""
    T = x.shape[2]
    t_valid = (jnp.arange(T)[None, None, :] <
               sub_lengths[:, :, None])[..., None]
    neg = jnp.finfo(x.dtype).min
    out = jnp.max(jnp.where(t_valid, x, neg), axis=2)
    return jnp.where(_sub_valid(lengths, sub_lengths)[..., None], out, 0.0)


def nested_pool_avg_per_sub(x: Array, lengths: Array, sub_lengths: Array,
                            strategy: str = "average") -> Array:
    """Per-sub-sequence mean/sum/sqrt-n: [B,S,T,D] -> [B,S,D]."""
    T = x.shape[2]
    t_valid = (jnp.arange(T)[None, None, :] <
               sub_lengths[:, :, None]).astype(x.dtype)[..., None]
    total = jnp.sum(x * t_valid, axis=2)
    if strategy != "sum":
        n = jnp.maximum(sub_lengths, 1).astype(x.dtype)[..., None]
        total = total / (jnp.sqrt(n) if strategy == "squarerootn" else n)
    return jnp.where(_sub_valid(lengths, sub_lengths)[..., None], total, 0.0)


def nested_pool_edge_per_sub(x: Array, lengths: Array, sub_lengths: Array,
                             first: bool) -> Array:
    """Per-sub-sequence first/last valid token: [B,S,T,D] -> [B,S,D]."""
    if first:
        out = x[:, :, 0]
    else:
        idx = jnp.maximum(sub_lengths - 1, 0)[:, :, None, None]
        out = jnp.take_along_axis(x, idx, axis=2)[:, :, 0]
    return jnp.where(_sub_valid(lengths, sub_lengths)[..., None], out, 0.0)
