"""Activation registry.

Mirrors the reference's activation zoo and registry-by-name
(ref: paddle/gserver/activations/ActivationFunction.cpp:67-317): identity,
sigmoid, softmax, sequence_softmax, relu, brelu, tanh, stanh, softrelu, abs,
square, exponential, log.  Forward-only pure functions — autodiff supplies
every backward the reference hand-wrote.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

activation_registry: dict[str, Callable[..., Array]] = {}


def _register(*names: str):
    def deco(fn):
        for n in names:
            activation_registry[n] = fn
        return fn
    return deco


@_register("", "linear", "identity")
def identity(x: Array, **_) -> Array:
    return x


@_register("sigmoid")
def sigmoid(x: Array, **_) -> Array:
    return jax.nn.sigmoid(x)


from paddle_tpu.utils.dtypes import promote_compute as _f32


@_register("softmax")
def softmax(x: Array, **_) -> Array:
    # fp32 exponentials/sum for stability, result back in the compute dtype
    # so bf16 doesn't silently leak to fp32 downstream (cost layers re-promote)
    return jax.nn.softmax(_f32(x), axis=-1).astype(x.dtype)


@_register("sequence_softmax")
def sequence_softmax(x: Array, mask: Optional[Array] = None, **_) -> Array:
    """Softmax across the time axis of a [B, T] (or [B, T, 1]) sequence of
    scalars, masked by validity (ref: SequenceSoftmaxActivation — softmax over
    each variable-length sequence's scalar scores, used by attention)."""
    squeeze = False
    in_dtype = x.dtype
    x = _f32(x)
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
        squeeze = True
    if mask is not None:
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=-1)
    if mask is not None:
        out = jnp.where(mask, out, 0.0)
    if squeeze:
        out = out[..., None]
    return out.astype(in_dtype)


@_register("relu")
def relu(x: Array, **_) -> Array:
    return jax.nn.relu(x)


@_register("brelu")
def brelu(x: Array, **_) -> Array:
    # bounded relu, clip to [0, 24] (ref: BReluActivation)
    return jnp.clip(x, 0.0, 24.0)


@_register("tanh")
def tanh(x: Array, **_) -> Array:
    return jnp.tanh(x)


@_register("stanh")
def stanh(x: Array, **_) -> Array:
    # scaled tanh 1.7159 * tanh(2/3 x) (ref: STanhActivation)
    return 1.7159 * jnp.tanh(x * (2.0 / 3.0))


@_register("softrelu")
def softrelu(x: Array, **_) -> Array:
    # log(1 + exp(x)), input clipped to +-40 (ref: SoftReluActivation)
    return jnp.log1p(jnp.exp(jnp.clip(x, -40.0, 40.0)))


@_register("abs")
def abs_(x: Array, **_) -> Array:
    return jnp.abs(x)


@_register("square")
def square(x: Array, **_) -> Array:
    return jnp.square(x)


@_register("gelu")
def gelu(x: Array, **_) -> Array:
    """tanh-approximated GELU (beyond the reference's zoo — the
    transformer-era nonlinearity; approximation keeps it MXU/VPU cheap)."""
    return jax.nn.gelu(x, approximate=True)


@_register("exponential")
def exponential(x: Array, **_) -> Array:
    return jnp.exp(x)


@_register("log")
def log(x: Array, **_) -> Array:
    return jnp.log(_f32(x))


def activation(name: str, x: Array, mask: Optional[Array] = None) -> Array:
    try:
        fn = activation_registry[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(activation_registry)}")
    return fn(x, mask=mask)
