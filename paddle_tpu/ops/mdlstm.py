"""Multi-dimensional (2-D) LSTM recurrence.

TPU-native analog of the reference's MDLstmLayer (ref:
paddle/gserver/layers/MDLstmLayer.cpp:180-486): each grid cell (i, j) has an
input node, an input gate, one forget gate *per dimension*, and an output
gate; its cell state mixes the predecessor states along every dimension
through the per-dimension forget gates; one shared recurrent weight matrix
[D, (3+n)D] projects every predecessor's hidden output into the gate
pre-activations, and peephole vectors live at the tail of the bias.

Re-design for XLA: the reference walks a `CoordIterator` over per-sequence
dynamic grid shapes; here the grid is static [H, W] (padded batches) and the
recurrence is a `lax.scan` over rows with an inner `lax.scan` over columns —
compile-friendly static control flow, one fused cell update per step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.activations import activation_registry

Array = jax.Array


def mdlstm_2d(
    x: Array,
    w: Array,
    bias: Array,
    height: int,
    width: int,
    directions: tuple[bool, bool] = (True, True),
    active_type: str = "tanh",
    gate_active_type: str = "sigmoid",
    state_active_type: str = "tanh",
    lengths: Array | None = None,
) -> Array:
    """Run a 2-D MDLSTM over a pre-projected grid.

    x:    [B, H*W, 5D] gate pre-activations in reference layout
          [inode | igate | fgate_dim0 | fgate_dim1 | ogate]
          (ref: MDLstmLayer.cpp:385-402 frame pointer offsets).
    w:    [D, 5D] shared recurrent weight (applied to each predecessor's h,
          ref: MDLstmLayer.cpp forwardOneSequence mul).
    bias: [(5 + 4)D] = local bias (5D) ++ peep_ig (D) ++ peep_fg (2D) ++
          peep_og (D) (ref: MDLstmLayer.cpp:228-258).
    directions[d]: True = scan dim d in increasing order.
    Returns h grid flattened back to [B, H*W, D].
    """
    B = x.shape[0]
    D = w.shape[0]
    G = 5 * D
    assert x.shape[1] == height * width and x.shape[2] == G
    assert bias.shape[-1] == 9 * D

    act = activation_registry[active_type or "tanh"]
    gate = activation_registry[gate_active_type or "sigmoid"]
    state_act = activation_registry[state_active_type or "tanh"]

    bias = bias.reshape(-1)
    local_b = bias[:G]
    peep_ig = bias[G:G + D]
    peep_fg0 = bias[G + D:G + 2 * D]
    peep_fg1 = bias[G + 2 * D:G + 3 * D]
    peep_og = bias[G + 3 * D:]

    # Padding cells (flat index >= lengths[b]) are treated as out-of-grid
    # boundary: their h/c are forced to zero so they contribute nothing to
    # neighbors — regardless of scan direction.  (The reference instead
    # carries per-sequence grid dims; uniform grids + masking is the
    # static-shape equivalent.)
    if lengths is not None:
        cell_valid = (jnp.arange(height * width)[None, :] < lengths[:, None])
        cell_valid = cell_valid.reshape(B, height, width, 1).astype(x.dtype)
    else:
        cell_valid = jnp.ones((B, height, width, 1), x.dtype)

    xg = (x + local_b).reshape(B, height, width, G)
    # Normalize to forward-forward scan; flip the input (and the output back)
    # for reversed dimensions — same trick the reference's CoordIterator
    # begin()/directions_ implements with index arithmetic.
    if not directions[0]:
        xg = jnp.flip(xg, 1)
        cell_valid = jnp.flip(cell_valid, 1)
    if not directions[1]:
        xg = jnp.flip(xg, 2)
        cell_valid = jnp.flip(cell_valid, 2)

    def cell(g: Array, h_up: Array, c_up: Array, h_left: Array, c_left: Array,
             v: Array):
        """One MDLSTM cell on [B, ...] slices (ref: forwardGate2OutputSequence)."""
        g = g + (h_up + h_left) @ w
        a = act(g[:, :D])
        zi = g[:, D:2 * D] + (c_up + c_left) * peep_ig
        zf0 = g[:, 2 * D:3 * D] + c_up * peep_fg0
        zf1 = g[:, 3 * D:4 * D] + c_left * peep_fg1
        i = gate(zi)
        f0 = gate(zf0)
        f1 = gate(zf1)
        c = f0 * c_up + f1 * c_left + a * i
        o = gate(g[:, 4 * D:] + c * peep_og)
        h = o * state_act(c)
        return h * v, c * v

    zeros = jnp.zeros((B, D), x.dtype)

    def row_step(carry, inp):
        # carry: previous row's (h, c) as [W, B, D]; x_row: [W, B, G]
        h_up_row, c_up_row = carry
        x_row, v_row = inp

        def col_step(cc, inp):
            h_left, c_left = cc
            g, h_up, c_up, v = inp
            h, c = cell(g, h_up, c_up, h_left, c_left, v)
            return (h, c), (h, c)

        (_, _), (h_row, c_row) = jax.lax.scan(
            col_step, (zeros, zeros), (x_row, h_up_row, c_up_row, v_row))
        return (h_row, c_row), h_row

    x_rows = jnp.transpose(xg, (1, 2, 0, 3))          # [H, W, B, G]
    v_rows = jnp.transpose(cell_valid, (1, 2, 0, 3))  # [H, W, B, 1]
    init = (jnp.zeros((width, B, D), x.dtype), jnp.zeros((width, B, D), x.dtype))
    _, h_all = jax.lax.scan(row_step, init, (x_rows, v_rows))  # [H, W, B, D]
    h = jnp.transpose(h_all, (2, 0, 1, 3))            # [B, H, W, D]

    if not directions[0]:
        h = jnp.flip(h, 1)
    if not directions[1]:
        h = jnp.flip(h, 2)
    return h.reshape(B, height * width, D)
