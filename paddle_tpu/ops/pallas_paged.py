"""Ragged paged decode attention in Pallas (TPU).

The serving engine's decode hot path (the Ragged Paged Attention shape,
arXiv:2604.15464): every slot's KV context lives in fixed-size pages of a
shared HBM pool, mapped by a per-slot page table, and each step attends ONE
query token per slot over its 0..pos positions.  The jnp fallback
(ops/attention.py:paged_attention_step) gathers the mapped pages into a
contiguous [S, max_pages*page_size] view every step — a transient HBM copy
of the whole context.  This kernel reads pages straight from the pool:

  grid (S, max_pages), pages innermost sequential: the page table rides a
  scalar-prefetch ref (pltpu.PrefetchScalarGridSpec) so the k/v BlockSpec
  index maps resolve `table[s, p]` BEFORE the DMA is issued — the pool
  page streams into VMEM with no gathered intermediate.  Per page, fold
  scores into a running online-softmax (max, sum, acc) VMEM scratch (the
  same recurrence as pallas_attention.py's flash kernel); pages past the
  slot's length are skipped entirely via pl.when (the "ragged" part — a
  slot holding 40 tokens reads 3 pages, not max_pages).

Grouped-query heads are handled in-kernel (per-kv-head score/weight dots,
a static python loop), so the pool stays at H_kv heads and no expanded
copy is ever materialized.  Sliding-window decode stays on the jnp
fallback.  Interpret-mode parity with the fallback is the CPU oracle
(tests/test_serving.py); on-TPU timing rides tools/bench_serving.py.

MIXED prefill/decode (chunked prefill): the optional `row_slot` operand
generalizes the query dimension from one-token-per-slot to a packed
ragged row list — row r attends table row `row_slot[r]` up to
`lengths[r]` tokens, so a prompt chunk (several consecutive rows, same
slot) and live decode rows share one grid.  `row_slot` rides the same
scalar-prefetch channel as the page table; everything else (online
softmax over live pages, pl.when page skipping, in-kernel GQA) is
unchanged.

SPECULATIVE verify rows (the engine's `--spec-k` draft chains) are the
same row-indirected shape from this kernel's point of view: a chain is
several consecutive rows of one slot at positions pos..pos+k, each
attending that slot's pages up to its own row — identical to a prompt
chunk except the K/V it reads at pos+1..pos+k was scattered
optimistically by the caller.  Rejection needs nothing from the
kernel: rejected positions sit beyond the slot's committed length,
masked for every later query and overwritten by the next chain before
pos can reach them (the rollback-safe-scatter contract documented on
ops/attention.py:ragged_paged_attention_step).

TENSOR PARALLELISM (the serving engine's `--mesh model=N` sharded
decode): this kernel is always invoked on LOCAL head shards — the
shard_map wrapper in ops/attention.py partitions q over its head axis
and the pools over their kv-head axis before calling in, so H and h_kv
here are the per-device counts (H/N and h_kv/N of the model; the engine
validates divisibility, and the grouped-query ratio H/h_kv is shard-
invariant).  The kernel itself needs no collective and no change: page
tables and lengths arrive replicated, every DMA stays on-chip, and the
head padding below (`max(H, 8)`) applies to the LOCAL count.

MULTI-STEP decode (the engine's `--decode-steps K` scanned dispatch):
the kernel is scan-body safe — pure in its operands with no host
callbacks, no side channels, and no per-call state, so `lax.scan`
tracing it K times produces ONE kernel instance in the loop body (the
body appears once in the HLO).  Positions/lengths arriving as scan
carries instead of host-staged arrays change nothing here: each body's
DMA addressing reads whatever `table`/`lengths` values the carry holds,
and under shard_map the same holds per shard (hlo_shard_check lowers
the scanned program and proves the collective set matches one body).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.utils.jax_compat import pallas_tpu_compiler_params

Array = jax.Array

_NEG_INF = -1e30


def supported(backend: Optional[str] = None) -> bool:
    """Whether the pallas ragged-paged kernel may be used."""
    if os.environ.get("PADDLE_TPU_PALLAS", "1") == "0":
        return False
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return True
    # off-TPU the kernel only runs in (slow) interpret mode — opt-in
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _kernel(H, h_kv, ps, scale, table_ref, len_ref, row_ref, q_ref, k_ref,
            v_ref, o_ref, m_s, l_s, acc_s):
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    s = pl.program_id(0)

    @pl.when(p == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    length = len_ref[s]

    @pl.when(p * ps < length)
    def _():
        rep = H // h_kv
        q = q_ref[0].astype(jnp.float32)                 # [Hp, Dp]
        k = k_ref[0].astype(jnp.float32)                 # [ps, h_kv, Dp]
        v = v_ref[0].astype(jnp.float32)
        # grouped-query scores: each kv head serves its rep query heads
        # (static python loop — h_kv is a compile-time constant)
        parts = []
        for g in range(h_kv):
            qg = q[g * rep:(g + 1) * rep, :]             # [rep, Dp]
            sg = jax.lax.dot_general(
                qg, k[:, g, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)      # [rep, ps]
            parts.append(sg)
        sc = jnp.concatenate(parts, axis=0) * scale      # [H, ps]
        tpos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (H, ps), 1)
        valid = tpos < length
        sc = jnp.where(valid, sc, _NEG_INF)

        m_prev = m_s[:H, :1]
        l_prev = l_s[:H, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        w = jnp.where(valid, jnp.exp(sc - m_new), 0.0)   # [H, ps]
        corr = jnp.exp(m_prev - m_new)
        l_s[:H, :1] = corr * l_prev + jnp.sum(w, axis=-1, keepdims=True)
        pv = []
        for g in range(h_kv):
            wg = w[g * rep:(g + 1) * rep, :]             # [rep, ps]
            pv.append(jax.lax.dot_general(
                wg, v[:, g, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))     # [rep, Dp]
        acc_s[:H] = acc_s[:H] * corr + jnp.concatenate(pv, axis=0)
        m_s[:H, :1] = m_new

    @pl.when(p == n_pages - 1)
    def _():
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)


def paged_attention(
    q: Array,               # [R, H, D] one query token per ROW
    k_pages: Array,         # [P, page_size, H_kv, D]
    v_pages: Array,         # [P, page_size, H_kv, D]
    page_table: Array,      # [S, max_pages] int32 (0 = unmapped)
    lengths: Array,         # [R] int32 valid tokens per row (incl. the
                            # just-written one: attend t < lengths[r])
    scale: Optional[float] = None,
    row_slot: Optional[Array] = None,   # [R] int32 page-table row each
                            # query row reads; None = rows ARE slots
                            # (the classic one-token-per-slot decode)
) -> Array:
    """Ragged paged attention -> [R, H, D].  Same math as the jnp
    fallback's gather path (online softmax re-association aside).

    `row_slot` is the MIXED prefill/decode generalization (the full
    ragged-query shape of arXiv:2604.15464): the query rows are no longer
    one-per-slot — a chunk-prefilling prompt packs several consecutive
    rows against the same page-table row, a decode slot keeps its single
    row, and padding rows aim at an all-zero table row.  The indirection
    rides the scalar-prefetch channel next to the page table, so the k/v
    BlockSpec index map resolves `table[row_slot[r], p]` before the page
    DMA is issued — same zero-copy pool streaming as the decode-only
    kernel, one compiled program for any prefill/decode mix."""
    R, H, D = q.shape
    P, ps, h_kv, _ = k_pages.shape
    maxp = page_table.shape[1]
    assert H % h_kv == 0, f"heads {H} not divisible by kv heads {h_kv}"
    if scale is None:
        scale = D ** -0.5
    if row_slot is None:
        row_slot = jnp.arange(R, dtype=jnp.int32)

    Hp = _round_up(max(H, 8), 8)
    Dp = _round_up(D, 128)
    qp = jnp.pad(q, ((0, 0), (0, Hp - H), (0, Dp - D)))
    kp = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, Dp - D)))
    vp = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, Dp - D)))

    kernel = functools.partial(_kernel, H, h_kv, ps, scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,               # page_table, lengths, row_slot
        grid=(R, maxp),
        in_specs=[
            pl.BlockSpec((1, Hp, Dp),
                         lambda s, p, tbl, lens, rows: (s, 0, 0)),
            pl.BlockSpec((1, ps, h_kv, Dp),
                         lambda s, p, tbl, lens, rows:
                         (tbl[rows[s], p], 0, 0, 0)),
            pl.BlockSpec((1, ps, h_kv, Dp),
                         lambda s, p, tbl, lens, rows:
                         (tbl[rows[s], p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hp, Dp),
                               lambda s, p, tbl, lens, rows: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hp, 128), jnp.float32),   # running max (lane 0)
            pltpu.VMEM((Hp, 128), jnp.float32),   # running sum (lane 0)
            pltpu.VMEM((Hp, Dp), jnp.float32),    # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, Hp, Dp), q.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      row_slot.astype(jnp.int32), qp, kp, vp)
    return out[:, :H, :D]
