"""Sampled output layers: NCE and hierarchical sigmoid.

TPU re-design of the reference's NCELayer + MultinomialSampler and
HierarchicalSigmoidLayer + MatrixBitCode (ref: paddle/gserver/layers/
{NCELayer,MultinomialSampler}.cpp, paddle/math/MatrixBitCode.cpp).  Sampling
uses jax.random.categorical (the alias-table of the reference is a CPU
construct); the bit-code path walk is vectorized over the class-id bits.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array
_EPS = 1e-10


def nce_cost(
    rng: Array,
    feats: list[Array],   # feature inputs, each [B, D_i] (contributions summed)
    labels: Array,        # [B]
    ws: list[Array],      # per-input class matrices [C, D_i]
    b: Optional[Array],   # [C] or None
    num_classes: int,
    num_neg: int,
    dist: Optional[Array] = None,   # [C] sampling distribution; None = uniform
) -> Array:
    """Binary-logistic NCE cost with `num_neg` shared negative samples
    (ref: NCELayer::forward — positive + sampled negatives through sigmoid CE,
    logits summed over all feature inputs)."""
    B = feats[0].shape[0]
    if dist is None:
        logdist = jnp.zeros((num_classes,))
        p_noise = jnp.full((num_classes,), 1.0 / num_classes)
    else:
        logdist = jnp.log(jnp.maximum(dist, _EPS))
        p_noise = dist
    neg = jax.random.categorical(rng, logdist, shape=(B, num_neg))
    if b is not None:
        b = b.reshape(-1)

    def logit(ids):  # ids [B, K] -> [B, K]
        z = None
        for feat, w in zip(feats, ws):
            wk = w[ids]                   # [B, K, D]
            zi = jnp.einsum("bkd,bd->bk", wk, feat)
            z = zi if z is None else z + zi
        if b is not None:
            z = z + b[ids]
        return z

    pos_z = logit(labels[:, None])        # [B, 1]
    neg_z = logit(neg)                    # [B, K]
    # NCE with noise-ratio correction: sigma(z - log(k * Pn(class)))
    pos_corr = jnp.log(num_neg * jnp.maximum(p_noise[labels[:, None]], _EPS))
    neg_corr = jnp.log(num_neg * jnp.maximum(p_noise[neg], _EPS))
    pos_cost = jax.nn.softplus(-(pos_z - pos_corr))[:, 0]
    neg_cost = jnp.sum(jax.nn.softplus(neg_z - neg_corr), axis=1)
    return pos_cost + neg_cost


def _bit_codes(labels: Array, num_bits: int) -> tuple[Array, Array]:
    """Huffman-free complete-binary-tree code of class id, matching the
    reference's SimpleCode (ref: MatrixBitCode.cpp SimpleCode: code(c)=c+1,
    node index at depth j = code>>(j+1)-1, bit = (code>>j)&1)."""
    code = labels + 1
    j = jnp.arange(num_bits)
    nodes = (code[:, None] >> (j + 1)[None, :]) - 1          # [B, nb]
    bits = (code[:, None] >> j[None, :]) & 1                 # [B, nb]
    valid = nodes >= 0
    return jnp.maximum(nodes, 0), jnp.where(valid, bits, -1)


def hsigmoid_cost(
    feats: list[Array],    # each [B, D_i]
    labels: Array,         # [B]
    ws: list[Array],       # each [num_classes-1, D_i] inner-node weights
    b: Optional[Array],    # [1, num_classes-1] (the bias-parameter layout)
    num_classes: int,
) -> Array:
    """sum over code bits of binary logistic cost
    (ref: HierarchicalSigmoidLayer::forward)."""
    num_bits = max(1, (num_classes - 1).bit_length())
    nodes, bits = _bit_codes(labels, num_bits)     # [B, nb]
    z = None
    for feat, w in zip(feats, ws):
        wn = w[nodes]                              # [B, nb, D]
        zi = jnp.einsum("bnd,bd->bn", wn, feat)
        z = zi if z is None else z + zi
    if b is not None:
        z = z + b.reshape(-1)[nodes]   # bias params arrive [1, C-1]
    valid = bits >= 0
    t = jnp.maximum(bits, 0).astype(z.dtype)
    # reference convention: bit=1 -> target sigmoid(z)=1
    cost_bits = jax.nn.softplus(z) - t * z
    return jnp.sum(jnp.where(valid, cost_bits, 0.0), axis=1)
