"""Recurrent cell scans.

TPU-native replacement for the reference's fused recurrent kernels and
timestep re-bucketing (ref: paddle/cuda/src/hl_cuda_lstm.cu
hl_lstm_parallel_forward/backward, include/hl_gru_ops.cuh, hl_lstm_ops.cuh,
gserver/layers/{LstmLayer,GatedRecurrentLayer,RecurrentLayer}.cpp and
SequenceToBatch.{h,cpp}).

Re-design: one `lax.scan` over the padded time axis.  Each step is a dense
[B, D] x [D, kD] GEMM on the MXU plus VPU elementwise gate math, which XLA
fuses exactly like the reference's hand-fused kernels.  Variable lengths are
handled by freezing the carried state once t >= length (a masked select) —
replacing SequenceToBatch's sort-by-length machinery with branch-free math.
Backward comes from autodiff through the scan.

Gate math matches the reference's cell definitions:
  LSTM (ref: hl_lstm_ops.cuh forward):
    a = act(xa + h.Wa)        i = gate(xi + h.Wi [+ c_prev*peep_i])
    f = gate(xf + h.Wf [+ c_prev*peep_f])
    c = a*i + f*c_prev        o = gate(xo + h.Wo [+ c*peep_o])
    h = o * state_act(c)
  GRU (ref: hl_gru_ops.cuh):
    u = gate(xu + h.Wu)       r = gate(xr + h.Wr)
    c = act(xc + (r*h).Wc)    h = u*h_prev + (1-u)*c
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.activations import activation_registry

Array = jax.Array


def _act(name: str):
    return activation_registry[name or "tanh"]


def _use_fused(D: int, *acts: str) -> bool:
    """Route to the Pallas fused kernels (ops/pallas_rnn.py) when profitable:
    on TPU with lane-aligned hidden size, or in interpret mode for tests."""
    from paddle_tpu.ops import pallas_rnn
    if not pallas_rnn.supported(None, *acts):
        return False
    if jax.default_backend() == "tpu":
        return D % 128 == 0
    return True  # interpret-mode opt-in (PADDLE_TPU_PALLAS_INTERPRET=1)


def lstm_scan(
    x4: Array,                  # [B, T, 4D] pre-projected input (order a,i,f,o)
    lengths: Array,             # [B]
    w_rec: Array,               # [D, 4D] recurrent weights
    bias: Optional[Array],      # [4D] or [7D] (with peepholes i,f,o) or None
    h0: Optional[Array] = None,  # [B, D] initial hidden
    c0: Optional[Array] = None,  # [B, D] initial cell
    active_type: str = "tanh",
    gate_active_type: str = "sigmoid",
    state_active_type: str = "tanh",
    reverse: bool = False,
) -> tuple[Array, Array, Array]:
    """Returns (hiddens [B,T,D], last_h [B,D], last_c [B,D])."""
    B, T, D4 = x4.shape
    D = D4 // 4
    act = _act(active_type)
    gate = _act(gate_active_type)
    state_act = _act(state_active_type)

    peep_i = peep_f = peep_o = None
    if bias is not None:
        bias = bias.reshape(-1)  # DSL creates [1, kD]; gate math wants 1-D
        if bias.shape[-1] == 7 * D:
            x4 = x4 + bias[: 4 * D]
            peep_i, peep_f, peep_o = bias[4 * D:5 * D], bias[5 * D:6 * D], bias[6 * D:]
        else:
            x4 = x4 + bias

    if h0 is None:
        h0 = jnp.zeros((B, D), x4.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), x4.dtype)

    if _use_fused(D, active_type, gate_active_type, state_active_type):
        from paddle_tpu.ops import pallas_rnn
        peeps = (jnp.stack([peep_i, peep_f, peep_o])
                 if peep_i is not None else jnp.zeros((3, D), x4.dtype))
        return pallas_rnn.lstm_fused(
            x4, lengths, w_rec, peeps, h0, c0,
            active_type=active_type, gate_active_type=gate_active_type,
            state_active_type=state_active_type, reverse=reverse)

    xs = jnp.moveaxis(x4, 1, 0)  # [T, B, 4D]
    ts = jnp.arange(T)
    if reverse:
        # scan the padded tail first so the valid prefix is visited in reverse
        # order; state stays frozen until t crosses into the valid range.
        xs = xs[::-1]
        ts = ts[::-1]

    def step(carry, inp):
        h, c = carry
        x_t, t = inp
        g = x_t + h @ w_rec
        a = act(g[:, :D])
        zi, zf, zo = g[:, D:2 * D], g[:, 2 * D:3 * D], g[:, 3 * D:]
        if peep_i is not None:
            zi = zi + c * peep_i
            zf = zf + c * peep_f
        i = gate(zi)
        f = gate(zf)
        c_new = a * i + f * c
        if peep_o is not None:
            zo = zo + c_new * peep_o
        o = gate(zo)
        h_new = o * state_act(c_new)
        valid = (t < lengths)[:, None]
        h = jnp.where(valid, h_new, h)
        c = jnp.where(valid, c_new, c)
        return (h, c), h

    (h_last, c_last), hs = lax.scan(step, (h0, c0), (xs, ts))
    if reverse:
        hs = hs[::-1]
    return jnp.moveaxis(hs, 0, 1), h_last, c_last


def gru_scan(
    x3: Array,                  # [B, T, 3D] pre-projected input (order u,r,c)
    lengths: Array,
    w_gate: Array,              # [D, 2D] recurrent weights for update/reset
    w_cand: Array,              # [D, D] recurrent weights for candidate
    bias: Optional[Array],      # [3D] or None
    h0: Optional[Array] = None,
    active_type: str = "tanh",
    gate_active_type: str = "sigmoid",
    reverse: bool = False,
) -> tuple[Array, Array]:
    """Returns (hiddens [B,T,D], last_h [B,D])."""
    B, T, D3 = x3.shape
    D = D3 // 3
    act = _act(active_type)
    gate = _act(gate_active_type)
    if bias is not None:
        x3 = x3 + bias.reshape(-1)
    if h0 is None:
        h0 = jnp.zeros((B, D), x3.dtype)

    if _use_fused(D, active_type, gate_active_type):
        from paddle_tpu.ops import pallas_rnn
        return pallas_rnn.gru_fused(
            x3, lengths, w_gate, w_cand, h0,
            active_type=active_type, gate_active_type=gate_active_type,
            reverse=reverse)

    xs = jnp.moveaxis(x3, 1, 0)
    ts = jnp.arange(T)
    if reverse:
        xs = xs[::-1]
        ts = ts[::-1]

    def step(h, inp):
        x_t, t = inp
        zg = x_t[:, : 2 * D] + h @ w_gate
        u = gate(zg[:, :D])
        r = gate(zg[:, D:])
        c = act(x_t[:, 2 * D:] + (r * h) @ w_cand)
        h_new = u * h + (1.0 - u) * c
        valid = (t < lengths)[:, None]
        h = jnp.where(valid, h_new, h)
        return h, h

    h_last, hs = lax.scan(step, h0, (xs, ts))
    if reverse:
        hs = hs[::-1]
    return jnp.moveaxis(hs, 0, 1), h_last


def simple_rnn_scan(
    x: Array,                   # [B, T, D] pre-projected input
    lengths: Array,
    w_rec: Array,               # [D, D]
    bias: Optional[Array],
    h0: Optional[Array] = None,
    active_type: str = "tanh",
    reverse: bool = False,
) -> tuple[Array, Array]:
    """Vanilla recurrent layer h_t = act(x_t + h_{t-1} W)
    (ref: RecurrentLayer.cpp forward)."""
    B, T, D = x.shape
    act = _act(active_type)
    if bias is not None:
        x = x + bias.reshape(-1)
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    xs = jnp.moveaxis(x, 1, 0)
    ts = jnp.arange(T)
    if reverse:
        xs = xs[::-1]
        ts = ts[::-1]

    def step(h, inp):
        x_t, t = inp
        h_new = act(x_t + h @ w_rec)
        valid = (t < lengths)[:, None]
        h = jnp.where(valid, h_new, h)
        return h, h

    h_last, hs = lax.scan(step, h0, (xs, ts))
    if reverse:
        hs = hs[::-1]
    return jnp.moveaxis(hs, 0, 1), h_last
