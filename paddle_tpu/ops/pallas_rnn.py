"""Fused recurrent kernels in Pallas (TPU).

TPU-native equivalent of the reference's hand-fused recurrent CUDA kernels
(ref: paddle/cuda/src/hl_cuda_lstm.cu hl_lstm_parallel_forward/
backward_data/backward_weight, include/hl_lstm_ops.cuh, hl_gru_ops.cuh).

Design: one `pallas_call` whose grid is the time axis.  The hidden/cell
state lives in VMEM scratch and persists across sequential grid steps, and
the recurrent weight is loaded into VMEM once — so the whole recurrence
runs without bouncing state through HBM, the same data-residency trick the
reference's kernels get from shared memory.  Each step is one [B,D]x[D,kD]
MXU matmul plus VPU gate math.  The backward pass is a second kernel
(custom_vjp) that walks time in reverse, recomputes the gate activations
from the stored per-step states (cheaper than storing them), and
accumulates the weight/peephole gradients in a VMEM scratch accumulator.

Variable lengths are handled branch-free: state freezes once t >= length
(mask select), identical to the lax.scan path in ops/rnn.py, which remains
the fallback for off-TPU backends and unaligned shapes.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# activation + derivative-from-output pairs usable inside kernels
_ACTS = {
    "sigmoid": (jax.nn.sigmoid, lambda y: y * (1.0 - y)),
    "tanh": (jnp.tanh, lambda y: 1.0 - y * y),
    "relu": (lambda x: jnp.maximum(x, 0.0), lambda y: (y > 0).astype(y.dtype)),
    "linear": (lambda x: x, lambda y: jnp.ones_like(y)),
    "": (lambda x: x, lambda y: jnp.ones_like(y)),
}


def supported(backend: Optional[str] = None, *acts: str) -> bool:
    """Whether the fused kernels may be used for this configuration."""
    if os.environ.get("PADDLE_TPU_PALLAS", "1") == "0":
        return False
    if any(a not in _ACTS for a in acts):
        return False
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return True
    # off-TPU the kernel only runs in (slow) interpret mode — opt-in for tests
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ===========================================================================
# LSTM
# ===========================================================================

def _lstm_fwd_kernel(T, D, reverse, act, gate, state_act,
                     x_ref, w_ref, peep_ref, lens_ref, h0_ref, c0_ref,
                     hs_ref, cs_ref, h_s, c_s):
    i = pl.program_id(0)
    act_f, _ = _ACTS[act]
    gate_f, _ = _ACTS[gate]
    state_f, _ = _ACTS[state_act]

    @pl.when(i == 0)
    def _():
        h_s[:] = h0_ref[:]
        c_s[:] = c0_ref[:]

    t = (T - 1 - i) if reverse else i
    h = h_s[:]
    c = c_s[:]
    g = x_ref[0] + jnp.dot(h, w_ref[:], preferred_element_type=jnp.float32)
    a = act_f(g[:, :D])
    ig = gate_f(g[:, D:2 * D] + c * peep_ref[0, :])
    fg = gate_f(g[:, 2 * D:3 * D] + c * peep_ref[1, :])
    c_new = a * ig + fg * c
    og = gate_f(g[:, 3 * D:] + c_new * peep_ref[2, :])
    h_new = og * state_f(c_new)

    valid = lens_ref[:] > t          # [B, 1] broadcast over D
    h2 = jnp.where(valid, h_new, h)
    c2 = jnp.where(valid, c_new, c)
    h_s[:] = h2
    c_s[:] = c2
    hs_ref[0] = h2
    cs_ref[0] = c2


def _lstm_bwd_kernel(T, D, reverse, act, gate, state_act,
                     x_ref, w_ref, peep_ref, lens_ref, h0_ref, c0_ref,
                     hsp_ref, csp_ref, cs_ref, ghs_ref, ghl_ref, gcl_ref,
                     dx_ref, dh0_ref, dc0_ref, dw_ref, dpeep_ref,
                     dh_s, dc_s, dw_s, dpeep_s):
    i = pl.program_id(0)
    s = T - 1 - i                      # scan-order step being differentiated
    act_f, act_d = _ACTS[act]
    gate_f, gate_d = _ACTS[gate]
    state_f, state_d = _ACTS[state_act]

    @pl.when(i == 0)
    def _():
        dh_s[:] = ghl_ref[:]
        dc_s[:] = gcl_ref[:]
        dw_s[:] = jnp.zeros_like(dw_s)
        dpeep_s[:] = jnp.zeros_like(dpeep_s)

    first = (s == 0)
    h_prev = jnp.where(first, h0_ref[:], hsp_ref[0])
    c_prev = jnp.where(first, c0_ref[:], csp_ref[0])
    c_new = cs_ref[0]

    # recompute gate activations (ref: hl_lstm backward recomputes from value)
    g = x_ref[0] + jnp.dot(h_prev, w_ref[:], preferred_element_type=jnp.float32)
    a = act_f(g[:, :D])
    ig = gate_f(g[:, D:2 * D] + c_prev * peep_ref[0, :])
    fg = gate_f(g[:, 2 * D:3 * D] + c_prev * peep_ref[1, :])
    og = gate_f(g[:, 3 * D:] + c_new * peep_ref[2, :])
    sc = state_f(c_new)

    t = (T - 1 - s) if reverse else s
    valid = lens_ref[:] > t

    dh_total = dh_s[:] + ghs_ref[0]
    do = dh_total * sc
    dzo = do * gate_d(og)
    dc_in = dh_total * og * state_d(sc) + dc_s[:] + dzo * peep_ref[2, :]
    da = dc_in * ig
    di = dc_in * a
    df = dc_in * c_prev
    dza = da * act_d(a)
    dzi = di * gate_d(ig)
    dzf = df * gate_d(fg)
    dc_prev = dc_in * fg + dzi * peep_ref[0, :] + dzf * peep_ref[1, :]

    dx4 = jnp.concatenate([dza, dzi, dzf, dzo], axis=1)
    dx4 = jnp.where(valid, dx4, 0.0)
    dx_ref[0] = dx4
    dh_prev = jnp.dot(dx4, w_ref[:].T, preferred_element_type=jnp.float32)
    dh_s[:] = jnp.where(valid, dh_prev, dh_total)
    dc_s[:] = jnp.where(valid, dc_prev, dc_s[:])
    dw_s[:] = dw_s[:] + jnp.dot(h_prev.T, dx4, preferred_element_type=jnp.float32)
    vm = valid.astype(jnp.float32)
    dpeep_s[0, :] = dpeep_s[0, :] + jnp.sum(dzi * c_prev * vm, axis=0)
    dpeep_s[1, :] = dpeep_s[1, :] + jnp.sum(dzf * c_prev * vm, axis=0)
    dpeep_s[2, :] = dpeep_s[2, :] + jnp.sum(dzo * c_new * vm, axis=0)

    @pl.when(i == T - 1)
    def _():
        dh0_ref[:] = dh_s[:]
        dc0_ref[:] = dc_s[:]
        dw_ref[:] = dw_s[:]
        dpeep_ref[:] = dpeep_s[:]


@functools.lru_cache(maxsize=None)
def _lstm_fused_factory(reverse: bool, act: str, gate: str, state_act: str):
    """Build the custom_vjp'd fused LSTM for one static configuration."""

    def fwd_call(xs, w, peeps, lens_f, h0, c0):
        T, B, D4 = xs.shape
        D = D4 // 4
        kern = functools.partial(_lstm_fwd_kernel, T, D, reverse,
                                 act, gate, state_act)
        hs, cs = pl.pallas_call(
            kern,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, D4), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),   # w
                pl.BlockSpec(memory_space=pltpu.VMEM),   # peeps
                pl.BlockSpec(memory_space=pltpu.VMEM),   # lens [B,1]
                pl.BlockSpec(memory_space=pltpu.VMEM),   # h0
                pl.BlockSpec(memory_space=pltpu.VMEM),   # c0
            ],
            out_specs=[
                pl.BlockSpec((1, B, D), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, B, D), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, D), jnp.float32),
                jax.ShapeDtypeStruct((T, B, D), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((B, D), jnp.float32),
                pltpu.VMEM((B, D), jnp.float32),
            ],
            interpret=_interpret(),
        )(xs, w, peeps, lens_f, h0, c0)
        return hs, cs

    @jax.custom_vjp
    def fused(xs, w, peeps, lens_f, h0, c0):
        hs, cs = fwd_call(xs, w, peeps, lens_f, h0, c0)
        return hs, hs[-1], cs[-1]

    def fused_fwd(xs, w, peeps, lens_f, h0, c0):
        hs, cs = fwd_call(xs, w, peeps, lens_f, h0, c0)
        return (hs, hs[-1], cs[-1]), (xs, w, peeps, lens_f, h0, c0, hs, cs)

    def fused_bwd(res, g):
        xs, w, peeps, lens_f, h0, c0, hs, cs = res
        g_hs, g_hl, g_cl = g
        T, B, D4 = xs.shape
        D = D4 // 4
        kern = functools.partial(_lstm_bwd_kernel, T, D, reverse,
                                 act, gate, state_act)
        step = pl.BlockSpec((1, B, D), lambda i: (T - 1 - i, 0, 0),
                            memory_space=pltpu.VMEM)
        # predecessor state: step s-1 = T-2-i, clamped (s==0 uses h0/c0)
        prev = pl.BlockSpec((1, B, D), lambda i: (jnp.maximum(T - 2 - i, 0), 0, 0),
                            memory_space=pltpu.VMEM)
        dx, dh0, dc0, dw, dpeep = pl.pallas_call(
            kern,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, D4), lambda i: (T - 1 - i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),   # w
                pl.BlockSpec(memory_space=pltpu.VMEM),   # peeps
                pl.BlockSpec(memory_space=pltpu.VMEM),   # lens
                pl.BlockSpec(memory_space=pltpu.VMEM),   # h0
                pl.BlockSpec(memory_space=pltpu.VMEM),   # c0
                prev,                                    # hs[s-1]
                prev,                                    # cs[s-1]
                step,                                    # cs[s]
                step,                                    # g_hs[s]
                pl.BlockSpec(memory_space=pltpu.VMEM),   # g_h_last
                pl.BlockSpec(memory_space=pltpu.VMEM),   # g_c_last
            ],
            out_specs=[
                pl.BlockSpec((1, B, D4), lambda i: (T - 1 - i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, D4), jnp.float32),
                jax.ShapeDtypeStruct((B, D), jnp.float32),
                jax.ShapeDtypeStruct((B, D), jnp.float32),
                jax.ShapeDtypeStruct((D, D4), jnp.float32),
                jax.ShapeDtypeStruct((3, D), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((B, D), jnp.float32),
                pltpu.VMEM((B, D), jnp.float32),
                pltpu.VMEM((D, D4), jnp.float32),
                pltpu.VMEM((3, D), jnp.float32),
            ],
            interpret=_interpret(),
        )(xs, w, peeps, lens_f, h0, c0, hs, cs, cs, g_hs, g_hl, g_cl)
        return dx, dw, dpeep, jnp.zeros_like(lens_f), dh0, dc0

    fused.defvjp(fused_fwd, fused_bwd)
    return fused


def lstm_fused(x4, lengths, w, peeps, h0, c0, *,
               active_type, gate_active_type, state_active_type, reverse):
    """Fused LSTM over [B, T, 4D] pre-projected (bias already added) input.

    peeps: [3, D] (i, f, o) peephole vectors (zeros when the layer has none).
    Returns (hs [B,T,D], h_last, c_last)."""
    B, T, D4 = x4.shape
    xs = jnp.moveaxis(x4, 1, 0).astype(jnp.float32)
    if reverse:
        # visit the padded tail first (scan order = reversed time); the
        # kernel masks with t = T-1-i so state freezes over the padding
        xs = xs[::-1]
    lens_f = lengths.astype(jnp.float32)[:, None]
    fused = _lstm_fused_factory(bool(reverse), active_type or "tanh",
                                gate_active_type or "sigmoid",
                                state_active_type or "tanh")
    hs, h_last, c_last = fused(xs, w.astype(jnp.float32),
                               peeps.astype(jnp.float32), lens_f,
                               h0.astype(jnp.float32), c0.astype(jnp.float32))
    if reverse:
        hs = hs[::-1]
    return jnp.moveaxis(hs, 0, 1), h_last, c_last


# ===========================================================================
# GRU
# ===========================================================================

def _gru_fwd_kernel(T, D, reverse, act, gate,
                    x_ref, wg_ref, wc_ref, lens_ref, h0_ref,
                    hs_ref, h_s):
    i = pl.program_id(0)
    act_f, _ = _ACTS[act]
    gate_f, _ = _ACTS[gate]

    @pl.when(i == 0)
    def _():
        h_s[:] = h0_ref[:]

    t = (T - 1 - i) if reverse else i
    h = h_s[:]
    x = x_ref[0]
    zg = x[:, :2 * D] + jnp.dot(h, wg_ref[:], preferred_element_type=jnp.float32)
    u = gate_f(zg[:, :D])
    r = gate_f(zg[:, D:])
    c = act_f(x[:, 2 * D:] + jnp.dot(r * h, wc_ref[:],
                                     preferred_element_type=jnp.float32))
    h_new = u * h + (1.0 - u) * c
    valid = lens_ref[:] > t
    h2 = jnp.where(valid, h_new, h)
    h_s[:] = h2
    hs_ref[0] = h2


def _gru_bwd_kernel(T, D, reverse, act, gate,
                    x_ref, wg_ref, wc_ref, lens_ref, h0_ref,
                    hsp_ref, ghs_ref, ghl_ref,
                    dx_ref, dh0_ref, dwg_ref, dwc_ref,
                    dh_s, dwg_s, dwc_s):
    i = pl.program_id(0)
    s = T - 1 - i
    act_f, act_d = _ACTS[act]
    gate_f, gate_d = _ACTS[gate]

    @pl.when(i == 0)
    def _():
        dh_s[:] = ghl_ref[:]
        dwg_s[:] = jnp.zeros_like(dwg_s)
        dwc_s[:] = jnp.zeros_like(dwc_s)

    h_prev = jnp.where(s == 0, h0_ref[:], hsp_ref[0])
    x = x_ref[0]
    zg = x[:, :2 * D] + jnp.dot(h_prev, wg_ref[:],
                                preferred_element_type=jnp.float32)
    u = gate_f(zg[:, :D])
    r = gate_f(zg[:, D:])
    rh = r * h_prev
    c = act_f(x[:, 2 * D:] + jnp.dot(rh, wc_ref[:],
                                     preferred_element_type=jnp.float32))

    t = (T - 1 - s) if reverse else s
    valid = lens_ref[:] > t

    dh_total = dh_s[:] + ghs_ref[0]
    du = dh_total * (h_prev - c)
    dc = dh_total * (1.0 - u)
    dzc = dc * act_d(c)
    drh = jnp.dot(dzc, wc_ref[:].T, preferred_element_type=jnp.float32)
    dr = drh * h_prev
    dzu = du * gate_d(u)
    dzr = dr * gate_d(r)
    dzg = jnp.concatenate([dzu, dzr], axis=1)
    dh_prev = (dh_total * u + drh * r +
               jnp.dot(dzg, wg_ref[:].T, preferred_element_type=jnp.float32))

    dx3 = jnp.concatenate([dzg, dzc], axis=1)
    dx3 = jnp.where(valid, dx3, 0.0)
    dx_ref[0] = dx3
    dh_s[:] = jnp.where(valid, dh_prev, dh_total)
    vm = valid.astype(jnp.float32)
    dwg_s[:] = dwg_s[:] + jnp.dot(h_prev.T, dzg * vm,
                                  preferred_element_type=jnp.float32)
    dwc_s[:] = dwc_s[:] + jnp.dot(rh.T, dzc * vm,
                                  preferred_element_type=jnp.float32)

    @pl.when(i == T - 1)
    def _():
        dh0_ref[:] = dh_s[:]
        dwg_ref[:] = dwg_s[:]
        dwc_ref[:] = dwc_s[:]


@functools.lru_cache(maxsize=None)
def _gru_fused_factory(reverse: bool, act: str, gate: str):
    def fwd_call(xs, wg, wc, lens_f, h0):
        T, B, D3 = xs.shape
        D = D3 // 3
        kern = functools.partial(_gru_fwd_kernel, T, D, reverse, act, gate)
        return pl.pallas_call(
            kern,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, D3), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, B, D), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((T, B, D), jnp.float32),
            scratch_shapes=[pltpu.VMEM((B, D), jnp.float32)],
            interpret=_interpret(),
        )(xs, wg, wc, lens_f, h0)

    @jax.custom_vjp
    def fused(xs, wg, wc, lens_f, h0):
        hs = fwd_call(xs, wg, wc, lens_f, h0)
        return hs, hs[-1]

    def fused_fwd(xs, wg, wc, lens_f, h0):
        hs = fwd_call(xs, wg, wc, lens_f, h0)
        return (hs, hs[-1]), (xs, wg, wc, lens_f, h0, hs)

    def fused_bwd(res, g):
        xs, wg, wc, lens_f, h0, hs = res
        g_hs, g_hl = g
        T, B, D3 = xs.shape
        D = D3 // 3
        kern = functools.partial(_gru_bwd_kernel, T, D, reverse, act, gate)
        step = pl.BlockSpec((1, B, D), lambda i: (T - 1 - i, 0, 0),
                            memory_space=pltpu.VMEM)
        prev = pl.BlockSpec((1, B, D), lambda i: (jnp.maximum(T - 2 - i, 0), 0, 0),
                            memory_space=pltpu.VMEM)
        dx, dh0, dwg, dwc = pl.pallas_call(
            kern,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((1, B, D3), lambda i: (T - 1 - i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                prev,
                step,
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, B, D3), lambda i: (T - 1 - i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, D3), jnp.float32),
                jax.ShapeDtypeStruct((B, D), jnp.float32),
                jax.ShapeDtypeStruct((D, 2 * D), jnp.float32),
                jax.ShapeDtypeStruct((D, D), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((B, D), jnp.float32),
                pltpu.VMEM((D, 2 * D), jnp.float32),
                pltpu.VMEM((D, D), jnp.float32),
            ],
            interpret=_interpret(),
        )(xs, wg, wc, lens_f, h0, hs, g_hs, g_hl)
        return dx, dwg, dwc, jnp.zeros_like(lens_f), dh0

    fused.defvjp(fused_fwd, fused_bwd)
    return fused


def gru_fused(x3, lengths, w_gate, w_cand, h0, *,
              active_type, gate_active_type, reverse):
    """Fused GRU over [B, T, 3D] pre-projected (bias already added) input.
    Returns (hs [B,T,D], h_last)."""
    xs = jnp.moveaxis(x3, 1, 0).astype(jnp.float32)
    if reverse:
        xs = xs[::-1]
    lens_f = lengths.astype(jnp.float32)[:, None]
    fused = _gru_fused_factory(bool(reverse), active_type or "tanh",
                               gate_active_type or "sigmoid")
    hs, h_last = fused(xs, w_gate.astype(jnp.float32),
                       w_cand.astype(jnp.float32), lens_f,
                       h0.astype(jnp.float32))
    if reverse:
        hs = hs[::-1]
    return jnp.moveaxis(hs, 0, 1), h_last
