"""Device op library — the TPU-native analog of the reference's hl_* kernel
surface (ref: paddle/cuda/include/hl_*.h) re-expressed as jnp functions that
XLA fuses, plus Pallas kernels for the few ops XLA can't schedule well.
"""

from paddle_tpu.ops.activations import activation, activation_registry  # noqa: F401
from paddle_tpu.ops import sequence  # noqa: F401
