"""Linear-chain CRF: log-likelihood and Viterbi decoding.

TPU re-design of the reference's LinearChainCRF (ref:
paddle/gserver/layers/LinearChainCRF.{h,cpp}: parameter layout w[0]=start
weights a, w[1]=end weights b, w[2:]=transition matrix [C,C]; forward() does
the alpha recursion per sequence, decode() Viterbi).  Here both are masked
`lax.scan`s over the padded time axis, batched over sequences, differentiable
by autodiff (the reference hand-writes the gradient in backward()).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


def _split(w: Array):
    a = w[0]          # start potentials [C]
    b = w[1]          # end potentials [C]
    trans = w[2:]     # transitions [C, C]; trans[i, j] = score(prev=i -> cur=j)
    return a, b, trans


def crf_log_z(x: Array, lengths: Array, w: Array) -> Array:
    """Log partition via alpha recursion: x [B,T,C] emission scores."""
    a, b, trans = _split(w)
    B, T, C = x.shape
    alpha0 = a[None, :] + x[:, 0]                     # [B, C]

    def step(alpha, inp):
        x_t, t = inp
        # logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None, :, :]       # [B, Cprev, Ccur]
        new = jax.nn.logsumexp(scores, axis=1) + x_t         # [B, C]
        valid = (t < lengths)[:, None]
        alpha = jnp.where(valid, new, alpha)
        return alpha, None

    xs = jnp.moveaxis(x, 1, 0)[1:]                    # [T-1, B, C]
    ts = jnp.arange(1, T)
    alpha, _ = lax.scan(step, alpha0, (xs, ts))
    return jax.nn.logsumexp(alpha + b[None, :], axis=-1)     # [B]


def crf_path_score(x: Array, labels: Array, lengths: Array, w: Array) -> Array:
    """Score of the gold path: emissions + transitions + start/end."""
    a, b, trans = _split(w)
    B, T, C = x.shape
    mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(x.dtype)
    emit = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]   # [B,T]
    score = jnp.sum(emit * mask, axis=1)
    score = score + a[labels[:, 0]]
    last = jnp.maximum(lengths - 1, 0)
    last_lbl = jnp.take_along_axis(labels, last[:, None], axis=1)[:, 0]
    score = score + b[last_lbl]
    pair = trans[labels[:, :-1], labels[:, 1:]]                          # [B,T-1]
    pair_mask = mask[:, 1:]
    return score + jnp.sum(pair * pair_mask, axis=1)


def crf_nll(x: Array, labels: Array, lengths: Array, w: Array) -> Array:
    """Per-sequence negative log likelihood (ref: LinearChainCRF::forward)."""
    return crf_log_z(x, lengths, w) - crf_path_score(x, labels, lengths, w)


def crf_decode(x: Array, lengths: Array, w: Array) -> Array:
    """Viterbi decode -> [B, T] int32 best path (ref: LinearChainCRF::decode)."""
    a, b, trans = _split(w)
    B, T, C = x.shape
    alpha0 = a[None, :] + x[:, 0]

    def fwd(alpha, inp):
        x_t, t = inp
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)        # [B, C]
        new = jnp.max(scores, axis=1) + x_t
        valid = (t < lengths)[:, None]
        alpha = jnp.where(valid, new, alpha)
        # freeze backpointers past the end: point to self
        best_prev = jnp.where(valid, best_prev, jnp.arange(C, dtype=jnp.int32)[None, :])
        return alpha, best_prev

    xs = jnp.moveaxis(x, 1, 0)[1:]
    ts = jnp.arange(1, T)
    alpha, bps = lax.scan(fwd, alpha0, (xs, ts))      # bps: [T-1, B, C]
    last_tag = jnp.argmax(alpha + b[None, :], axis=-1).astype(jnp.int32)  # [B]

    def back(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    # reverse scan over transitions: prevs[t] = tag at position t (t=0..T-2)
    _, prevs = lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([prevs, last_tag[None, :]], axis=0)   # [T, B]
    return jnp.moveaxis(path, 0, 1)                   # [B, T]
