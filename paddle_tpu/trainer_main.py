"""paddle_tpu trainer CLI — `python -m paddle_tpu.trainer_main --config=...`.

TPU-native analog of the `paddle_trainer` binary (ref:
paddle/trainer/TrainerMain.cpp:36-110: flag parsing, config load, job
dispatch train/test/checkgrad/time).  The pserver self-hosting flags are gone
— distribution is a mesh + jax.distributed, not a server fleet.
"""

from __future__ import annotations

import sys

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import mesh_from_flag
from paddle_tpu.trainer.trainer import Trainer
from paddle_tpu.utils import FLAGS, get_logger, parse_flags

log = get_logger("main")


def main(argv=None) -> int:
    rest = parse_flags(argv)
    if not FLAGS.config:
        print("usage: python -m paddle_tpu.trainer_main --config=<config.py> "
              "[--job=train|test|checkgrad|time] [--num_passes=N] "
              "[--save_dir=DIR] [--config_args=k=v,...] [--mesh_shape=data:8] "
              "[--steps_per_dispatch=K] [--detect_nan] [--profile_dir=DIR] "
              "[--show_parameter_stats_period=N]", file=sys.stderr)
        return 2

    if FLAGS.coordinator_address:
        from paddle_tpu.parallel.mesh import init_distributed
        init_distributed(FLAGS.coordinator_address, FLAGS.num_processes,
                         FLAGS.process_id)
        log.info("joined cluster as process %d/%d (coordinator %s)",
                 FLAGS.process_id, FLAGS.num_processes,
                 FLAGS.coordinator_address)

    if FLAGS.detect_nan:
        # FP-anomaly trapping (ref: feenableexcept(FE_INVALID|...) at trainer
        # start, TrainerMain.cpp:97; utils/Excepts.h): XLA re-runs the
        # offending computation uncompiled and raises at the bad primitive
        jax.config.update("jax_debug_nans", True)

    try:
        config = parse_config(FLAGS.config, FLAGS.config_args)
    except Exception as e:   # noqa: BLE001 — configs run arbitrary user code
        # ANY failure while parsing/executing the config file is a usage
        # error (exit 2), not a job failure (exit 1) — wrapper scripts
        # branch on the distinction; exc_info keeps the config-side
        # traceback visible so the offending statement is findable
        log.error("failed to parse config %s: %s: %s", FLAGS.config,
                  type(e).__name__, e, exc_info=True)
        return 2
    log.info("parsed config %s: %d layers, %d parameters", FLAGS.config,
             len(config.model_config.layers), len(config.model_config.parameters))
    mesh = mesh_from_flag(FLAGS.mesh_shape) if FLAGS.mesh_shape else None
    if mesh is not None:
        log.info("mesh: %s over %d devices", dict(zip(mesh.axis_names, mesh.devices.shape)),
                 mesh.devices.size)

    trainer = Trainer(config, seed=FLAGS.seed, mesh=mesh)
    if FLAGS.init_model_path:
        trainer.load(FLAGS.init_model_path)
        log.info("loaded initial model from %s", FLAGS.init_model_path)

    if FLAGS.profile_dir:
        # device-side tracing (ref: REGISTER_TIMER/WITH_TIMER Stat.h:130-256
        # + hl_profiler_start/end -> jax.profiler traces viewable in
        # tensorboard/xprof)
        jax.profiler.start_trace(FLAGS.profile_dir)

    job = FLAGS.job
    try:
        if job == "train":
            trainer.train(num_passes=FLAGS.num_passes, log_period=FLAGS.log_period,
                          save_dir=FLAGS.save_dir or None)
        elif job == "test":
            if trainer.config.test_data_config is None:
                log.error("--job=test: this config declares no test data "
                          "source — add define_py_data_sources2("
                          "test_list=...) (ref: TrainerMain.cpp)")
                return 2
            stats = trainer.test()
            log.info("test result: %s", stats)
        elif job == "time":
            stats = trainer.benchmark(trainer.train_batches())
            log.info("benchmark: %.1f samples/sec (%d samples in %.2fs)",
                     stats["samples_per_sec"], stats["samples"], stats["seconds"])
        elif job == "checkgrad":
            batch = next(iter(trainer.train_batches()), None)
            if batch is None:
                log.error("checkgrad: data source produced no batches")
                return 2
            errors = trainer.check_gradient(
                batch, refine_threshold=FLAGS.checkgrad_bar)
            worst = max(errors.values(), default=0.0)
            log.info("checkgrad: %d parameters, worst max_rel_err=%.3e",
                     len(errors), worst)
            if worst > FLAGS.checkgrad_bar:
                log.error("gradient check FAILED")
                return 1
        else:
            log.error("unknown --job=%s", job)
            return 2
    finally:
        if FLAGS.profile_dir:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", FLAGS.profile_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
