"""paddle_tpu trainer CLI — `python -m paddle_tpu.trainer_main --config=...`.

TPU-native analog of the `paddle_trainer` binary (ref:
paddle/trainer/TrainerMain.cpp:36-110: flag parsing, config load, job
dispatch train/test/checkgrad/time).  The pserver self-hosting flags are gone
— distribution is a mesh + jax.distributed, not a server fleet.
"""

from __future__ import annotations

import sys

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import mesh_from_flag
from paddle_tpu.trainer.trainer import Trainer
from paddle_tpu.utils import FLAGS, get_logger, parse_flags

log = get_logger("main")


def main(argv=None) -> int:
    rest = parse_flags(argv)
    if not FLAGS.config:
        print("usage: python -m paddle_tpu.trainer_main --config=<config.py> "
              "[--job=train|test|time] [--num_passes=N] [--save_dir=DIR] "
              "[--config_args=k=v,...] [--mesh_shape=data:8]", file=sys.stderr)
        return 2

    config = parse_config(FLAGS.config, FLAGS.config_args)
    log.info("parsed config %s: %d layers, %d parameters", FLAGS.config,
             len(config.model_config.layers), len(config.model_config.parameters))
    mesh = mesh_from_flag(FLAGS.mesh_shape) if FLAGS.mesh_shape else None
    if mesh is not None:
        log.info("mesh: %s over %d devices", dict(zip(mesh.axis_names, mesh.devices.shape)),
                 mesh.devices.size)

    trainer = Trainer(config, seed=FLAGS.seed, mesh=mesh)
    if FLAGS.init_model_path:
        trainer.load(FLAGS.init_model_path)
        log.info("loaded initial model from %s", FLAGS.init_model_path)

    job = FLAGS.job
    if job == "train":
        trainer.train(num_passes=FLAGS.num_passes, log_period=FLAGS.log_period,
                      save_dir=FLAGS.save_dir or None)
    elif job == "test":
        stats = trainer.test()
        log.info("test result: %s", stats)
    elif job == "time":
        stats = trainer.benchmark(trainer.train_batches())
        log.info("benchmark: %.1f samples/sec (%d samples in %.2fs)",
                 stats["samples_per_sec"], stats["samples"], stats["seconds"])
    else:
        log.error("unknown --job=%s", job)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
