"""Paged KV cache: a fixed pool of [num_pages, page_size, h_kv, dh] pages
per attention layer plus per-slot page tables.

Replaces the dense `lm_decode.init_kv_caches` layout for SERVING: a dense
cache sizes every row at P+max_new whatever the row actually holds, and its
[B, total, ...] shape bakes the request mix into the compiled program.
Here the pool shape is fixed forever — one compiled decode step serves any
request mix — and HBM cost is proportional to pages actually allocated
(Ragged Paged Attention, arXiv:2604.15464; the slot/page serving
configuration of arXiv:2605.25645).

Device side: per-attention-layer page pools (`pools[name]["k"/"v"]`) that
thread through the engine's jitted decode step, and ONE logical page table
shared by every layer (all layers hold the same tokens).  Host side: the
page allocator — a free list plus the per-slot table mirror the scheduler
consults and mutates between steps.  PHYSICAL PAGE 0 IS RESERVED as the
trash page: unmapped table entries are 0, so inactive/paused slots' writes
land there and reads of unallocated logical pages gather finite garbage
that causal masking weighs to exactly 0 (see
ops/attention.py:paged_attention_step).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


class PagedKVCache:
    """Device page pools + host page allocator for `num_slots` decode slots.

    `pages_per_slot * page_size` bounds one slot's context (prompt +
    generated); `num_pages` bounds the whole pool (default: worst case,
    every slot full, plus the trash page — pass something smaller to
    overcommit, the engine then pauses slots/defers admission when the
    free list runs dry)."""

    def __init__(self, executor, num_slots: int, page_size: int,
                 pages_per_slot: int, num_pages: Optional[int] = None):
        assert page_size > 0 and pages_per_slot > 0
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.num_slots = int(num_slots)
        self.num_pages = int(num_pages) if num_pages else \
            1 + num_slots * pages_per_slot
        assert self.num_pages >= 2, "pool needs the trash page + 1 real page"

        dtype = jnp.dtype(executor.compute_dtype) if executor.compute_dtype \
            else jnp.float32
        self.layer_specs: dict[str, tuple[int, int]] = {}
        self.pools: dict[str, dict[str, jnp.ndarray]] = {}
        for l in executor.model.layers:
            if l.type != "multi_head_attention":
                continue
            heads = int(l.attrs["num_heads"])
            h_kv = int(l.attrs.get("num_kv_heads", 0) or heads)
            dh = int(l.size) // heads
            self.layer_specs[l.name] = (h_kv, dh)
            self.pools[l.name] = {
                "k": jnp.zeros((self.num_pages, page_size, h_kv, dh), dtype),
                "v": jnp.zeros((self.num_pages, page_size, h_kv, dh), dtype),
            }
        assert self.pools, "model has no multi_head_attention layers to page"

        # host allocator state: table[s, j] = physical page backing logical
        # page j of slot s (0 = unmapped -> trash)
        self.table = np.zeros((num_slots, pages_per_slot), np.int32)
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._n_pages = np.zeros(num_slots, np.int32)

    # -- capacity ---------------------------------------------------------
    @property
    def capacity_tokens(self) -> int:
        """Max tokens (prompt + generated) one slot can hold."""
        return self.pages_per_slot * self.page_size

    @property
    def free_page_count(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    # -- allocator --------------------------------------------------------
    def try_grow(self, slot: int, n_tokens: int) -> bool:
        """Ensure `slot` has pages covering `n_tokens` tokens, allocating
        from the free list on demand.  False (and no change beyond pages
        already grabbed — they stay with the slot for the retry) when the
        free list runs dry: the caller pauses the slot or defers the
        admission."""
        need = self.pages_for(n_tokens)
        assert need <= self.pages_per_slot, \
            f"slot {slot}: {n_tokens} tokens exceed the " \
            f"{self.capacity_tokens}-token slot capacity"
        while self._n_pages[slot] < need:
            if not self._free:
                return False
            page = self._free.pop()
            self.table[slot, self._n_pages[slot]] = page
            self._n_pages[slot] += 1
        return True

    def release(self, slot: int) -> None:
        """Return every page of `slot` to the free list (retire/abort)."""
        for j in range(int(self._n_pages[slot])):
            self._free.append(int(self.table[slot, j]))
        self.table[slot, :] = 0
        self._n_pages[slot] = 0

    def reset(self) -> None:
        """Release every slot (pool contents need no zeroing: stale pages
        are unreachable once unmapped, and masked if ever gathered)."""
        for s in range(self.num_slots):
            self.release(s)
