"""Paged KV cache: a fixed pool of [num_pages, page_size, h_kv, dh] pages
per attention layer plus per-slot page tables.

Replaces the dense `lm_decode.init_kv_caches` layout for SERVING: a dense
cache sizes every row at P+max_new whatever the row actually holds, and its
[B, total, ...] shape bakes the request mix into the compiled program.
Here the pool shape is fixed forever — one compiled decode step serves any
request mix — and HBM cost is proportional to pages actually allocated
(Ragged Paged Attention, arXiv:2604.15464; the slot/page serving
configuration of arXiv:2605.25645).

Device side: per-attention-layer page pools (`pools[name]["k"/"v"]`) that
thread through the engine's jitted decode step, and ONE logical page table
shared by every layer (all layers hold the same tokens).  Host side: the
page allocator — a free list plus the per-slot table mirror the scheduler
consults and mutates between steps.  PHYSICAL PAGE 0 IS RESERVED as the
trash page: unmapped table entries are 0, so inactive/paused slots' writes
land there and reads of unallocated logical pages gather finite garbage
that causal masking weighs to exactly 0 (see
ops/attention.py:paged_attention_step).

PREFIX SHARING (PR 7): physical pages are REFCOUNTED so one committed page
can back the same prompt prefix in many slots at once (and sit in the
prefix index, serving/prefix_tree.py, between requests).  The contract:

  * `_ref[p]` counts slot-table mappings of physical page p; `_cached[p]`
    marks pages held read-only by the prefix index.  A page returns to the
    free list only when BOTH drop away.
  * a page with `_ref > 1` or `_cached` set is SHARED and must never be
    written — the engine calls `ensure_writable` before any write into a
    mapped page, which COWs a private copy (device page copy + remap) when
    the page is shared.
  * when the free list runs dry the allocator first asks
    `on_page_pressure(n)` (the prefix index's LRU eviction) to reclaim
    cached refcount-zero pages — eviction before pausing slots, preemption
    stays last resort.

HOST SPILL TIER (docs/serving.md "KV spill tier"): with a non-zero
`spill_bytes_budget`, a cold refcount-zero cached page that the prefix
index would otherwise destroy under page pressure is instead COPIED to a
host-RAM buffer (one `[page_size, h_kv, dh]` ndarray per layer per page)
and the device page freed — the effective prefix cache grows past HBM.
The tier is bounded by the byte budget with LRU eviction INSIDE it (the
prefix index drops its least-recently-used host-resident leaves to make
room), and an admission that prefix-hits a spilled run restores the
pages: `take_pages` allocates fresh device pages, `restore_pages`
scatters the host copies back in ONE batched dispatch (page-count
bucketed to powers of two, pad rows writing zeros to trash page 0, so
signatures stay bounded), and `adopt_restored` re-marks them cached
before the slot maps them read-only.  Restores are MOVES — the host copy
is dropped, a later re-spill re-copies.  All of it is admission-boundary
host/allocator work: the decode/mixed/spec/scan step signatures never
see the tier.  `_host_gen` stamps every entry and bumps on reset(), so a
stale spilled page can never restore tokens from a dead tree generation.

TENSOR PARALLELISM (PR 11): constructed with a mesh whose `model` axis
exceeds 1, the pools shard on their kv-head axis (`PartitionSpec(None,
None, "model", None)`) — each device's HBM holds only its heads' slice of
every page, so the servable KV grows with the mesh while the ALLOCATOR is
untouched: tables, refcounts, the free list and the prefix index are
host-side and shard-agnostic (a physical page is one logical unit whose
storage happens to be split).  `version` stamps every host table write so
the engine re-uploads its device-resident table only when something
actually changed (the hot decode loop's zero-restaging contract).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


class PagedKVCache:
    """Device page pools + host page allocator for `num_slots` decode slots.

    `pages_per_slot * page_size` bounds one slot's context (prompt +
    generated); `num_pages` bounds the whole pool (default: worst case,
    every slot full, plus the trash page — pass something smaller to
    overcommit, the engine then evicts cached prefixes / pauses slots /
    defers admission when the free list runs dry)."""

    def __init__(self, executor, num_slots: int, page_size: int,
                 pages_per_slot: int, num_pages: Optional[int] = None,
                 mesh=None, spill_bytes_budget: int = 0):
        assert page_size > 0 and pages_per_slot > 0
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.num_slots = int(num_slots)
        self.num_pages = int(num_pages) if num_pages else \
            1 + num_slots * pages_per_slot
        assert self.num_pages >= 2, "pool needs the trash page + 1 real page"

        # tensor parallelism: pools shard on their kv-head axis over the
        # mesh `model` axis — each device's HBM holds only its heads'
        # pages, so the servable KV grows with the mesh (the engine
        # validates h_kv divisibility; tables stay host/replicated).
        # `pool_sharding` is THE canonical pool placement — the engine's
        # step in_shardings and every pool-writing jit pin to it.
        from paddle_tpu.parallel.mesh import MODEL_AXIS, axis_size

        self.mesh = mesh
        self.pool_sharding = None
        self.tp_shards = axis_size(mesh, MODEL_AXIS)
        if self.tp_shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            self.pool_sharding = NamedSharding(
                mesh, PartitionSpec(None, None, MODEL_AXIS, None))

        dtype = jnp.dtype(executor.compute_dtype) if executor.compute_dtype \
            else jnp.float32
        self.layer_specs: dict[str, tuple[int, int]] = {}
        self.pools: dict[str, dict[str, jnp.ndarray]] = {}
        for l in executor.model.layers:
            if l.type != "multi_head_attention":
                continue
            heads = int(l.attrs["num_heads"])
            h_kv = int(l.attrs.get("num_kv_heads", 0) or heads)
            dh = int(l.size) // heads
            self.layer_specs[l.name] = (h_kv, dh)
            shape = (self.num_pages, page_size, h_kv, dh)

            def _pool():
                # distinct buffers per part — k and v are donated side by
                # side, and XLA refuses to donate one buffer twice
                z = jnp.zeros(shape, dtype)
                return jax.device_put(z, self.pool_sharding) \
                    if self.pool_sharding is not None else z

            self.pools[l.name] = {"k": _pool(), "v": _pool()}
        assert self.pools, "model has no multi_head_attention layers to page"

        # host allocator state: table[s, j] = physical page backing logical
        # page j of slot s (0 = unmapped -> trash)
        self.table = np.zeros((num_slots, pages_per_slot), np.int32)
        # monotone table-write stamp: every host-side table/allocator
        # mutation bumps it, and the engine re-uploads its device-resident
        # table ONLY when it moved — the hot decode loop's zero-restaging
        # contract hangs off this counter
        self.version = 0
        self._free = self._canonical_free()
        self._n_pages = np.zeros(num_slots, np.int32)
        # per-physical-page slot-mapping refcount + prefix-index membership
        self._ref = np.zeros(self.num_pages, np.int32)
        self._cached = np.zeros(self.num_pages, bool)
        # called with the page shortfall when the free list runs dry;
        # returns pages reclaimed (the prefix index's LRU eviction —
        # serving/engine.py wires it).  None = no reclaimer, fail dry.
        self.on_page_pressure: Optional[Callable[[int], int]] = None
        self.n_cow = 0                 # copy-on-write page copies performed
        self._copy_fn = None           # lazily-jitted device page copy
        # -- host spill tier (module docstring "HOST SPILL TIER") ----------
        # hid -> {"gen", "nbytes", "data": {layer: (k_np, v_np)}}; the
        # prefix index owns the POLICY (who spills, who drops) — this is
        # the mechanism + the byte accounting
        self.spill_bytes_budget = int(spill_bytes_budget or 0)
        self._host: dict[int, dict] = {}
        self._next_hid = 1
        self._host_bytes = 0
        self._host_gen = 0             # bumped on reset(): the stale-spill
                                       # generation guard
        self.n_spilled = 0             # pages spilled device -> host (ever)
        self.n_restored = 0            # pages restored host -> device (ever)
        self.n_host_evicted = 0        # host-tier LRU drops (budget pressure)
        self._host_drained = 0         # non-evict, non-restore drops
        self._restore_fns: dict[int, object] = {}   # bucketed jitted scatter
        # -- cross-replica page transfer (docs/serving.md "Disaggregated
        # prefill/decode"): committed pages serialized to/from host bytes
        self.n_exported = 0            # pages exported to wire bytes (ever)
        self.n_imported = 0            # pages imported from wire bytes (ever)

    def _canonical_free(self) -> list:
        """The free list in its construction-time canonical order (pop()
        hands out page 1 first) — reset() rebuilds exactly this, so page
        placement is reproducible across engine restarts."""
        return list(range(self.num_pages - 1, 0, -1))

    # -- capacity ---------------------------------------------------------
    @property
    def capacity_tokens(self) -> int:
        """Max tokens (prompt + generated) one slot can hold."""
        return self.pages_per_slot * self.page_size

    @property
    def free_page_count(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        """Pages not on the free list: slot-mapped (private or shared) plus
        pages retained only by the prefix index."""
        return (self.num_pages - 1) - len(self._free)

    @property
    def private_pages_in_use(self) -> int:
        """Pages mapped by exactly one slot and not in the prefix index."""
        return int(np.sum((self._ref == 1) & ~self._cached))

    @property
    def shared_pages_in_use(self) -> int:
        """Slot-mapped pages that are shared: mapped by >1 slot, or mapped
        while also held by the prefix index (read-only either way)."""
        return int(np.sum((self._ref >= 1) &
                          ((self._ref > 1) | self._cached)))

    @property
    def cached_page_count(self) -> int:
        """Pages held ONLY by the prefix index — reclaimable by eviction."""
        return int(np.sum((self._ref == 0) & self._cached))

    @property
    def pool_bytes(self) -> int:
        """Total device bytes of the K/V page pools (all shards)."""
        return sum(int(p[part].size) * p[part].dtype.itemsize
                   for p in self.pools.values() for part in ("k", "v"))

    @property
    def pool_bytes_per_shard(self) -> int:
        """Pool bytes resident PER DEVICE: the kv-head axis splits over
        the mesh model axis, so each shard holds 1/tp of every page."""
        return self.pool_bytes // self.tp_shards

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    # -- allocator --------------------------------------------------------
    def _alloc_page(self) -> Optional[int]:
        """Pop one free page, asking the pressure hook (prefix-index LRU
        eviction) to reclaim when the list is dry.  None = genuinely out."""
        if not self._free and self.on_page_pressure is not None:
            self.on_page_pressure(1)
        if not self._free:
            return None
        page = self._free.pop()
        assert self._ref[page] == 0 and not self._cached[page], \
            f"free list held a referenced page {page}"
        return page

    def try_grow(self, slot: int, n_tokens: int, evict: bool = True) -> bool:
        """Ensure `slot` has pages covering `n_tokens` tokens, allocating
        from the free list on demand (evicting cached prefixes under
        pressure).  False (and no change beyond pages already grabbed —
        they stay with the slot for the retry) when the pool is genuinely
        dry: the caller pauses the slot or defers the admission.

        `evict=False` takes FREE pages only — the speculative draft-tail
        growth uses it, because optimistic pages that a rejection hands
        straight back the same step must never cost a committed cached
        prefix its retention (a low-accept spec workload would otherwise
        churn the prefix index to back K/V it immediately discards); the
        caller shrinks its draft ambition to what is genuinely free."""
        need = self.pages_for(n_tokens)
        assert need <= self.pages_per_slot, \
            f"slot {slot}: {n_tokens} tokens exceed the " \
            f"{self.capacity_tokens}-token slot capacity"
        # ask for the whole shortfall in ONE pressure call (one tree walk),
        # not page-by-page through _alloc_page's single-page fallback
        shortfall = (need - int(self._n_pages[slot])) - len(self._free)
        if shortfall > 0 and evict and self.on_page_pressure is not None:
            if shortfall > self.cached_page_count:
                # infeasible even after evicting EVERY reclaimable page:
                # fail fast WITHOUT evicting.  A doomed retry must not
                # destroy cached prefixes it cannot use — in particular a
                # preempted half-chunked prefill's donated pages, which
                # its own re-admission retries against every step until
                # another slot frees the remainder (the retry that can
                # finally succeed still finds them and prefix-hits)
                return False
            self.on_page_pressure(shortfall)
        while self._n_pages[slot] < need:
            if not self._free and not evict:
                return False
            page = self._alloc_page()
            if page is None:
                return False
            self._ref[page] = 1
            self.table[slot, self._n_pages[slot]] = page
            self._n_pages[slot] += 1
            self.version += 1
        return True

    def map_shared(self, slot: int, pages) -> None:
        """Map already-committed (prefix-index) pages read-only into an
        EMPTY slot's table as its first logical pages — the prefix-hit
        admission path.  Bumps each page's refcount; the pages must never
        be written through this slot until `ensure_writable` COWs them."""
        assert self._n_pages[slot] == 0, \
            f"slot {slot} is not empty — shared prefixes map at admission"
        assert len(pages) <= self.pages_per_slot
        for j, page in enumerate(pages):
            page = int(page)
            assert 0 < page < self.num_pages and (
                self._ref[page] > 0 or self._cached[page]), \
                f"page {page} is not a live committed page"
            self._ref[page] += 1
            self.table[slot, j] = page
        self._n_pages[slot] = len(pages)
        self.version += 1

    def page_writable(self, page: int) -> bool:
        return self._ref[page] == 1 and not self._cached[page]

    def ensure_writable(self, slot: int, j: int) -> Optional[bool]:
        """Make logical page `j` of `slot` safe to write: if the mapped
        physical page is shared (multi-mapped or prefix-cached), allocate a
        private page, device-copy the contents, and remap.  Returns True if
        a COW copy happened, False if the page was already private, None if
        a copy was needed but the pool is dry (caller rolls back)."""
        assert j < self._n_pages[slot], f"slot {slot} has no logical page {j}"
        page = int(self.table[slot, j])
        if self.page_writable(page):
            return False
        fresh = self._alloc_page()
        if fresh is None:
            return None
        self.pools = self._page_copy()(self.pools, fresh, page)
        self._ref[fresh] = 1
        self.table[slot, j] = fresh
        self.version += 1
        self._unref(page)
        self.n_cow += 1
        return True

    def _unref(self, page: int) -> None:
        assert self._ref[page] >= 1, \
            f"page {page} unreferenced below zero (double release?)"
        self._ref[page] -= 1
        if self._ref[page] == 0 and not self._cached[page]:
            self._free.append(page)

    def release(self, slot: int) -> None:
        """Drop every mapping of `slot` (retire/abort): each page's
        refcount decrements, and pages no slot maps and the prefix index
        does not hold return to the free list.  Idempotent — a second
        release (or a release after reset()) is a no-op, it can never
        append the same physical page to the free list twice."""
        for j in range(int(self._n_pages[slot])):
            self._unref(int(self.table[slot, j]))
        self.table[slot, :] = 0
        self._n_pages[slot] = 0
        self.version += 1

    def uncommit_tail(self, slot: int, n_tokens: int) -> int:
        """Release the slot's trailing pages beyond `pages_for(n_tokens)`
        — the SPECULATIVE-DECODE page rollback: the verify step wrote
        draft K/V optimistically into pages grown past the slot's
        committed length, and a rejected suffix leaves those tail pages
        holding only garbage the causal mask already excludes.  The
        on-device state needs no cleanup (future writes overwrite the
        garbage positions before any query can attend them); THIS is the
        host half — hand the unjustified pages back to the pool so a
        rejection never inflates occupancy past what preempt/replay
        would charge.  Tail pages are always PRIVATE (drafts never write
        shared pages; growth allocates fresh ones) — asserted, since
        releasing a shared page here would corrupt a cached prefix.
        Returns the number of pages released."""
        keep = self.pages_for(n_tokens)
        freed = 0
        while int(self._n_pages[slot]) > keep:
            j = int(self._n_pages[slot]) - 1
            page = int(self.table[slot, j])
            assert self.page_writable(page), \
                f"slot {slot}: uncommit_tail hit shared page {page} at " \
                f"logical index {j} — draft writes must never target " \
                f"shared pages"
            self.table[slot, j] = 0
            self._n_pages[slot] -= 1
            self._unref(page)
            freed += 1
        if freed:
            self.version += 1
        return freed

    def reset(self) -> None:
        """Release every slot AND forget all prefix-index retention, then
        rebuild the free list in CANONICAL order — page placement after a
        reset is bit-reproducible across engine restarts (exactness tests
        and postmortem engine.json snapshots stay stable).  The caller
        owning a prefix index must clear it too (its nodes' pages are no
        longer retained here); ServingEngine.reset_prefix_cache does both.
        Pool contents need no zeroing: stale pages are unreachable once
        unmapped, and masked if ever gathered."""
        self.table[:, :] = 0
        self._n_pages[:] = 0
        self._ref[:] = 0
        self._cached[:] = False
        self._free = self._canonical_free()
        # drain the host tier and bump the generation: a spilled page
        # surviving a cache reset would restore K/V from a dead tree
        # generation — any hid a caller still holds now fails
        # host_entry_live and the admission falls back to cold prefill
        self._host_drained += len(self._host)
        self._host.clear()
        self._host_bytes = 0
        self._host_gen += 1
        self.version += 1

    # -- prefix-index retention -------------------------------------------
    def cache_page(self, page: int) -> None:
        """Mark `page` as held by the prefix index (called at donation —
        the donor slot still maps it, so it cannot be on the free list)."""
        page = int(page)
        assert 0 < page < self.num_pages
        assert self._ref[page] >= 1, \
            f"page {page} donated to the prefix index without a live mapping"
        self._cached[page] = True

    def uncache_page(self, page: int) -> None:
        """Drop prefix-index retention of `page` (eviction); frees it when
        no slot maps it either."""
        page = int(page)
        assert self._cached[page], f"page {page} is not prefix-cached"
        self._cached[page] = False
        if self._ref[page] == 0:
            self._free.append(page)

    # -- host spill tier ---------------------------------------------------
    @property
    def page_nbytes(self) -> int:
        """Host bytes one spilled page costs: k + v across every layer."""
        itemsize = next(iter(self.pools.values()))["k"].dtype.itemsize
        return sum(2 * self.page_size * h_kv * dh * itemsize
                   for (h_kv, dh) in self.layer_specs.values())

    @property
    def host_page_count(self) -> int:
        return len(self._host)

    @property
    def host_bytes(self) -> int:
        return self._host_bytes

    def host_entry_live(self, hid) -> bool:
        """The generation guard: an entry from before the last reset()
        (or one already dropped) must never restore."""
        e = self._host.get(int(hid))
        return e is not None and e["gen"] == self._host_gen

    def spill_page(self, page: int) -> Optional[int]:
        """Copy a cold cached page's K/V to the host tier and free the
        device page — the evict-to-host half of two-level eviction.
        Returns the host id the caller (the prefix index) stores on its
        node, or None when the budget cannot hold one page (the caller
        destroys instead).  The caller makes budget room FIRST by
        dropping its own host-LRU victims via drop_host_page.  The
        device->host copy forces a device sync, which is fine here: page
        pressure fires at admission boundaries, never inside a step."""
        page = int(page)
        assert self._ref[page] == 0 and self._cached[page], \
            f"page {page} is not a cold cached page — only refcount-zero " \
            f"prefix-index pages spill"
        nbytes = self.page_nbytes
        if self._host_bytes + nbytes > self.spill_bytes_budget:
            return None
        data = {name: (np.asarray(self.pools[name]["k"][page]),
                       np.asarray(self.pools[name]["v"][page]))
                for name in self.pools}
        hid = self._next_hid
        self._next_hid += 1
        self._host[hid] = {"gen": self._host_gen, "nbytes": nbytes,
                           "data": data}
        self._host_bytes += nbytes
        self.n_spilled += 1
        self._cached[page] = False          # uncache_page for ref==0, but
        self._free.append(page)             # the contents live on as `hid`
        self.version += 1
        return hid

    def drop_host_page(self, hid, reason: str = "evict") -> None:
        """Forget one host entry.  `reason` keeps the conservation ledger
        exact: "evict" = host-tier LRU budget pressure (n_host_evicted),
        "drain" = cache clear / re-donation / stale-gen cleanup
        (_host_drained), "restore" = the move to device (restore_pages
        counts it as n_restored).  Tolerates an already-drained entry —
        reset() empties the tier wholesale and the tree's clear() walk
        follows it."""
        e = self._host.pop(int(hid), None)
        if e is None:
            return
        self._host_bytes -= e["nbytes"]
        if reason == "evict":
            self.n_host_evicted += 1
        elif reason == "drain":
            self._host_drained += 1

    def take_pages(self, n: int) -> Optional[list]:
        """Pop `n` free pages for a host-tier restore WITHOUT binding
        them to a slot table (the engine scatters the host copies in,
        then adopt_restored + the tree's promote re-establish prefix
        retention).  One pressure call for the whole shortfall, like
        try_grow.  Returns None — nothing taken — when the pool cannot
        cover it; untake_pages rolls back a taken batch exactly."""
        n = int(n)
        shortfall = n - len(self._free)
        if shortfall > 0 and self.on_page_pressure is not None:
            if shortfall > self.cached_page_count:
                return None
            self.on_page_pressure(shortfall)
        if len(self._free) < n:
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert self._ref[p] == 0 and not self._cached[p], \
                f"free list held a referenced page {p}"
        self.version += 1
        return pages

    def untake_pages(self, pages) -> None:
        """Return a take_pages batch to the free list in the exact order
        it came off — page placement stays reproducible on rollback."""
        for p in reversed(pages):
            self._free.append(int(p))
        self.version += 1

    def adopt_restored(self, pages) -> None:
        """Mark freshly-restored pages as prefix-index retained.  Unlike
        cache_page (donation: the donor slot still maps the page) a
        restored page has no mapping yet — the restoring slot's
        map_shared follows immediately."""
        for p in pages:
            p = int(p)
            assert 0 < p < self.num_pages and self._ref[p] == 0 and \
                not self._cached[p], f"page {p} is not a fresh taken page"
            self._cached[p] = True

    def restore_pages(self, hids, pages) -> None:
        """Batched host->device restore: scatter each host entry's K/V
        into its taken device page in ONE jitted dispatch per
        reservation.  Page count buckets to the next power of two (pad
        rows write zeros to trash page 0) so compiled signatures are
        bounded by log2(num_pages), never by restore-batch diversity.
        MOVE semantics: the host copies drop here — a later re-spill
        re-copies."""
        n = len(hids)
        assert n == len(pages) and n > 0
        bucket = 1
        while bucket < n:
            bucket *= 2
        idx = np.zeros(bucket, np.int32)            # pad -> trash page 0
        idx[:n] = pages
        ks: dict = {}
        vs: dict = {}
        for name in self.pools:
            h_kv, dh = self.layer_specs[name]
            dtype = np.dtype(self.pools[name]["k"].dtype)
            k = np.zeros((bucket, self.page_size, h_kv, dh), dtype)
            v = np.zeros_like(k)
            for i, hid in enumerate(hids):
                e = self._host[int(hid)]
                k[i], v[i] = e["data"][name]
            ks[name], vs[name] = k, v
        self.pools = self._restore_fn(bucket)(
            self.pools, jnp.asarray(idx), ks, vs)
        for hid in hids:
            self.drop_host_page(hid, reason="restore")
        self.n_restored += n

    def _restore_fn(self, bucket: int):
        if bucket not in self._restore_fns:
            def scatter(pools, pages, ks, vs):
                # duplicate pad indices all write zeros to the trash
                # page, so the scatter's write order is immaterial
                return {name: {
                    "k": pools[name]["k"].at[pages].set(ks[name]),
                    "v": pools[name]["v"].at[pages].set(vs[name]),
                } for name in pools}

            from paddle_tpu.obs.compile_watch import get_compile_watch
            kw = {}
            if self.pool_sharding is not None:
                # same canonical-pool-sharding pin as the COW copy — a
                # drifted layout would reshard every pool next step
                kw["out_shardings"] = {
                    name: {"k": self.pool_sharding,
                           "v": self.pool_sharding}
                    for name in self.pools}
            self._restore_fns[bucket] = get_compile_watch().wrap_jit(
                "serving.spill_restore",
                jax.jit(scatter, donate_argnums=(0,), **kw))
        return self._restore_fns[bucket]

    # -- cross-replica page transfer ---------------------------------------
    def export_pages(self, pages) -> tuple[dict, bytes]:
        """Serialize live committed pages to host bytes — the kv_push
        transfer plane's sender half (docs/serving.md "Disaggregated
        prefill/decode").  One batched device->host gather per layer part
        in the spill tier's per-layer ndarray layout: the payload is the
        concatenation, over layers in SORTED name order, of the k block
        then the v block, each `[n, page_size, h_kv, dh]` row-major.
        Returns `(meta, payload)` where meta names the shapes/dtypes the
        importer must match exactly.  Pages must be live (slot-mapped or
        prefix-cached) — exporting a free page would ship garbage."""
        pages = [int(p) for p in pages]
        assert pages, "export_pages needs at least one page"
        for p in pages:
            assert 0 < p < self.num_pages and (
                self._ref[p] > 0 or self._cached[p]), \
                f"page {p} is not a live committed page"
        idx = np.asarray(pages, np.int32)
        names = sorted(self.pools)
        parts = []
        layers = []
        for name in names:
            h_kv, dh = self.layer_specs[name]
            k = np.ascontiguousarray(np.asarray(self.pools[name]["k"][idx]))
            v = np.ascontiguousarray(np.asarray(self.pools[name]["v"][idx]))
            parts.append(k.tobytes())
            parts.append(v.tobytes())
            layers.append({"name": name, "h_kv": h_kv, "dh": dh,
                           "dtype": str(k.dtype)})
        meta = {"n_pages": len(pages), "page_size": self.page_size,
                "layers": layers}
        self.n_exported += len(pages)
        return meta, b"".join(parts)

    def import_pages(self, meta: dict, payload: bytes, pages) -> None:
        """Scatter an export_pages blob into freshly-taken device pages —
        the kv_push receiver half.  Validates EVERYTHING (page count,
        page size, layer set, per-layer shapes/dtypes, exact payload
        length) before touching any device state and raises ValueError on
        mismatch, so the caller's `untake_pages(pages)` rollback restores
        the allocator exactly (`check()` stays green on partial failure).
        The scatter reuses the spill tier's pow2-bucketed restore jit —
        one dispatch, pad rows writing zeros to trash page 0."""
        n = len(pages)
        if int(meta.get("n_pages", -1)) != n:
            raise ValueError(
                f"kv import: blob holds {meta.get('n_pages')} pages, "
                f"caller took {n}")
        if int(meta.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"kv import: page_size {meta.get('page_size')} != "
                f"pool page_size {self.page_size}")
        layers = meta.get("layers") or []
        if [l.get("name") for l in layers] != sorted(self.pools):
            raise ValueError(
                f"kv import: layer set {[l.get('name') for l in layers]} "
                f"!= pool layers {sorted(self.pools)}")
        total = 0
        for l in layers:
            h_kv, dh = self.layer_specs[l["name"]]
            dtype = np.dtype(self.pools[l["name"]]["k"].dtype)
            if int(l.get("h_kv", -1)) != h_kv or int(l.get("dh", -1)) != dh \
                    or str(l.get("dtype")) != str(dtype):
                raise ValueError(
                    f"kv import: layer {l['name']!r} shape/dtype "
                    f"({l.get('h_kv')},{l.get('dh')},{l.get('dtype')}) != "
                    f"pool ({h_kv},{dh},{dtype})")
            total += 2 * n * self.page_size * h_kv * dh * dtype.itemsize
        if len(payload) != total:
            raise ValueError(
                f"kv import: payload is {len(payload)} bytes, "
                f"meta declares {total}")
        for p in pages:
            p = int(p)
            assert 0 < p < self.num_pages and self._ref[p] == 0 and \
                not self._cached[p], f"page {p} is not a fresh taken page"
        bucket = 1
        while bucket < n:
            bucket *= 2
        idx = np.zeros(bucket, np.int32)            # pad -> trash page 0
        idx[:n] = [int(p) for p in pages]
        ks: dict = {}
        vs: dict = {}
        off = 0
        for l in layers:
            name = l["name"]
            h_kv, dh = self.layer_specs[name]
            dtype = np.dtype(self.pools[name]["k"].dtype)
            nb = n * self.page_size * h_kv * dh * dtype.itemsize
            shape = (n, self.page_size, h_kv, dh)
            k = np.zeros((bucket,) + shape[1:], dtype)
            v = np.zeros_like(k)
            k[:n] = np.frombuffer(payload, dtype, count=nb // dtype.itemsize,
                                  offset=off).reshape(shape)
            off += nb
            v[:n] = np.frombuffer(payload, dtype, count=nb // dtype.itemsize,
                                  offset=off).reshape(shape)
            off += nb
            ks[name], vs[name] = k, v
        self.pools = self._restore_fn(bucket)(
            self.pools, jnp.asarray(idx), ks, vs)
        self.n_imported += n

    # -- device page copy (COW) -------------------------------------------
    def _page_copy(self):
        if self._copy_fn is None:
            def copy(pools, dst, src):
                return {name: {
                    "k": pools[name]["k"].at[dst].set(pools[name]["k"][src]),
                    "v": pools[name]["v"].at[dst].set(pools[name]["v"][src]),
                } for name in pools}

            from paddle_tpu.obs.compile_watch import get_compile_watch
            kw = {}
            if self.pool_sharding is not None:
                # sharded pools must come back in the canonical pool
                # sharding — a drifted layout would force the next decode
                # step's explicit in_shardings to reshard every pool
                kw["out_shardings"] = {
                    name: {"k": self.pool_sharding,
                           "v": self.pool_sharding}
                    for name in self.pools}
            self._copy_fn = get_compile_watch().wrap_jit(
                "serving.cow_copy", jax.jit(copy, donate_argnums=(0,), **kw))
        return self._copy_fn

    # -- debugging / test oracle ------------------------------------------
    def check(self) -> None:
        """Assert the allocator invariants (tests call this after
        workloads): refcounts agree with the tables, the free list is
        exactly the unreferenced-and-uncached pages, no duplicates."""
        ref = np.zeros(self.num_pages, np.int32)
        for s in range(self.num_slots):
            for j in range(int(self._n_pages[s])):
                page = int(self.table[s, j])
                assert 0 < page < self.num_pages, \
                    f"slot {s} maps invalid page {page}"
                ref[page] += 1
        assert (ref == self._ref).all(), \
            f"refcounts disagree with tables: {self._ref.tolist()} vs " \
            f"recomputed {ref.tolist()}"
        free = set(self._free)
        assert len(free) == len(self._free), "free list holds duplicates"
        expect = {p for p in range(1, self.num_pages)
                  if self._ref[p] == 0 and not self._cached[p]}
        assert free == expect, \
            f"free list {sorted(free)} != unreferenced pages {sorted(expect)}"
        assert not self._cached[0] and self._ref[0] == 0, \
            "trash page 0 must never be referenced or cached"
        # host-tier accounting: bytes agree with the entries, every entry
        # belongs to the CURRENT generation (reset drains wholesale, so a
        # stale-gen entry means a drain was skipped), and the tier honors
        # its budget (empty when spilling is off)
        assert self._host_bytes == sum(
            e["nbytes"] for e in self._host.values()), \
            f"host-tier bytes {self._host_bytes} disagree with entries"
        assert all(e["gen"] == self._host_gen
                   for e in self._host.values()), \
            "host tier holds entries from a dead generation"
        assert self._host_bytes <= self.spill_bytes_budget, \
            f"host tier {self._host_bytes}B exceeds the " \
            f"{self.spill_bytes_budget}B spill budget"

    def check_reclaimed(self) -> None:
        """check() plus the end-of-workload invariant: no slot holds
        pages (private or shared), and everything off the free list is
        retained ONLY by the prefix index — evictable on demand, so the
        pool is fully reclaimable even though retired pages stay cached.
        Two-tier conservation: device free + device cached account for
        the whole pool (spilled pages freed their device page the moment
        their contents moved to host), and the spill/restore/evict
        counters reconcile against the host pages still resident."""
        self.check()
        assert self.private_pages_in_use == 0, \
            f"{self.private_pages_in_use} private pages still slot-mapped"
        assert self.shared_pages_in_use == 0, \
            f"{self.shared_pages_in_use} shared pages still slot-mapped"
        assert self.free_page_count + self.cached_page_count == \
            self.num_pages - 1, \
            f"free {self.free_page_count} + cached " \
            f"{self.cached_page_count} != pool {self.num_pages - 1}"
        assert self.host_page_count == \
            self.n_spilled - self.n_restored - self.n_host_evicted - \
            self._host_drained, \
            f"host tier {self.host_page_count} pages != spilled " \
            f"{self.n_spilled} - restored {self.n_restored} - evicted " \
            f"{self.n_host_evicted} - drained {self._host_drained}"
