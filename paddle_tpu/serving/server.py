"""RPC serving front end: asyncio TCP server + continuous engine pump.

The reference's layer 5 is a length-prefixed RPC socket server in front of
the compute (ref: paddle/pserver/ProtoServer.h:37, LightNetwork.h:41);
this is its TPU-native serving echo — a request-lifecycle front end
(admission, deadlines, cancellation, streaming, drain — the architecture
production TPU serving stacks put in front of a continuous-batching core,
arXiv:2605.25645) over `serving/engine.py`:

  * ONE background PUMP THREAD owns the ServingEngine and drives step()
    continuously — requests arrive mid-flight, per-token completions
    stream back as they decode.  All engine access goes through the pump:
    the asyncio side never touches scheduler state, it posts commands
    (add/cancel) to a thread-safe queue the pump drains between steps, and
    the engine's on_token/on_finish hooks post frames back via
    call_soon_threadsafe.  No locks around the scheduler, no torn state.
  * BOUNDED ADMISSION: the server accepts at most
    `num_slots + max_queue` unfinished requests; one more gets an explicit
    `overload` response instead of unbounded queueing (the client backs
    off; the queue never eats the host).
  * DEADLINES and CANCELLATION free the request's slot and KV pages
    mid-flight (engine.cancel / the per-step deadline sweep) — freed pages
    are reusable by waiting requests on the very next step, and surviving
    requests stay token-exact against the per-request lm_generate oracle
    (tests/test_server.py).
  * GRACEFUL DRAIN: stop admitting (new requests get
    `overload/reason=draining`), finish everything in flight, stop the
    pump, close the listener.  tools/serve.py wires SIGTERM to this and
    exits 0.
  * STATS RPC: queue depth, slot/page occupancy, preemptions, and
    per-request / per-token latency percentiles from a utils/stat.py
    StatSet (bounded sample windows — a week-old server reports recent
    latency, not its lifetime average).  The engine-state part of the
    snapshot is built ON THE PUMP THREAD via a command-queue round trip,
    so `slots_in_use`/`pages_in_use`/`queue_depth` are mutually
    consistent (between-steps view); `{"stale_ok": true}` keeps the old
    loop-thread fast path for pollers that must never wait on the pump
    (the watchdog's path — it also works when the pump is wedged).
  * METRICS + WATCHDOG: a Prometheus-style `metrics` frame (obs.metrics
    registry — engine counters, admission state, latency quantiles,
    tracer accounting) answered on the LOOP thread so it stays readable
    while the pump is wedged; the pump heartbeats every loop iteration
    and `pump_last_step_age_s` exposes a hung engine in metrics before
    clients time out.

Wire protocol: serving/wire.py (4-byte big-endian length + JSON body);
message schemas in docs/serving.md.  The blocking-socket client is
serving/client.py.
"""

from __future__ import annotations

import asyncio
import json
import queue
import sys
import threading
import time
from typing import Optional

import numpy as np

from paddle_tpu.obs import (MetricsRegistry, statset_collector,
                            tracer_collector)
from paddle_tpu.obs.compile_watch import compile_collector, get_compile_watch
from paddle_tpu.obs.flight import flight_collector, get_flight_recorder
from paddle_tpu.obs.hbm import hbm_collector, hbm_snapshot
from paddle_tpu.obs.slo import SloEvaluator, default_serving_slos
from paddle_tpu.obs.timeseries import (HistorySampler, MetricHistory,
                                       history_collector, history_reply)
from paddle_tpu.obs.trace import trace_reply
from paddle_tpu.serving import wire
from paddle_tpu.serving.engine import Request, ServingEngine
from paddle_tpu.utils.stat import StatSet


class _ReqState:
    """Server-side lifecycle of one accepted request."""

    __slots__ = ("conn", "cid", "stream", "t_submit", "t_last", "next_idx",
                 "burst_left", "burst_share", "push_to", "prompt")

    def __init__(self, conn, cid, stream):
        self.conn = conn
        self.cid = cid                # the client's id (frame field)
        self.stream = bool(stream)
        # disaggregated prefill (docs/serving.md): a prefill_only request
        # carries the decode replica to kv_push the committed pages to —
        # the done frame is then DELAYED until the push resolves, so the
        # router learns push_ok before it sends the real generate
        self.push_to = None           # {"host", "port"} or None
        self.prompt = None            # np.int32 prompt (prefill_only only)
        self.t_submit = time.monotonic()
        self.t_last = self.t_submit   # last token emission (TTFT base)
        self.next_idx = 0             # next UNSEEN token index — a
                                      # preempted request replays identical
                                      # tokens from 0; indexes below this
                                      # are dropped, not re-streamed
        # burst-honest inter-token latency (multi-step decode): a scanned
        # dispatch banks up to k tokens back-to-back, so the first token
        # of a burst divides the whole inter-arrival gap by the burst size
        # and the rest charge the SAME share — token_latency percentiles
        # stay comparable across decode_steps settings
        self.burst_left = 0           # burst tokens still to charge
        self.burst_share = 0.0        # per-token share of the burst gap


#: one client connection (asyncio side): the shared slow-reader-severing
#: frame connection — hoisted to wire.py so the fleet router's client
#: face can never drift from this server's (conn.rids maps client id ->
#: engine req_id here)
_Conn = wire.FrameConn


def _kv_push_frames(cid, toks, meta: dict, payload: bytes) -> list[bytes]:
    """Split one kv_push blob into encoded BIN frames, each under the
    receiver's MAX_BIN_PAYLOAD bin_cap.  The cap bounds the WHOLE
    declared body (header-length word + JSON header + chunk), and part
    0's header carries the full token list + per-layer meta — for long
    prompts that header alone runs to hundreds of KiB, so the part-0
    chunk is sized from the ENCODED header rather than a fixed headroom
    (a fixed 64 KiB reserve silently busts the cap past ~9k tokens —
    exactly the prompts --disagg-min-prompt selects for).  Raises
    wire.FrameError when even an empty-chunk part 0 would exceed the cap
    (the caller degrades to push_ok:false)."""
    tokens = [int(t) for t in toks]
    probe = {"type": "kv_push", "id": cid, "seq": 0, "last": False,
             "tokens": tokens, "meta": meta}
    h0 = len(json.dumps(probe, separators=(",", ":")).encode("utf-8"))
    # 64 bytes absorb the real header's drift from this probe (the
    # length word, last:true vs false)
    room0 = wire.MAX_BIN_PAYLOAD - h0 - 64
    if room0 < 0:
        raise wire.FrameError(
            f"kv_push part-0 header is {h0} bytes, over the "
            f"{wire.MAX_BIN_PAYLOAD}-byte binary-frame cap")
    # later parts carry a tiny header; 4096 bytes of slack covers it at
    # any seq digit count
    chunk = wire.MAX_BIN_PAYLOAD - 4096
    parts = [payload[:room0]]
    parts += [payload[i:i + chunk]
              for i in range(len(parts[0]), len(payload), chunk)]
    frames = []
    for i, part in enumerate(parts):
        hdr = {"type": "kv_push", "id": cid, "seq": i,
               "last": i == len(parts) - 1}
        if i == 0:
            hdr["tokens"] = tokens
            hdr["meta"] = meta
        frames.append(wire.encode_bin(hdr, part))
    return frames


class ServingServer:
    """TCP front end over one ServingEngine.

    >>> eng = ServingEngine(tr.executor, tr.params, num_slots=4)
    >>> srv = ServingServer(eng, port=0)           # 0 = ephemeral
    >>> host, port = srv.start_background()
    >>> ...                                        # serving/client.py
    >>> srv.stop_background(drain=True)

    `max_queue` bounds requests accepted beyond the engine's slots:
    admission cap = num_slots + max_queue unfinished requests.
    """

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, max_queue: int = 32,
                 postmortem_dir: Optional[str] = None,
                 wedge_threshold_s: float = 30.0, role: str = "both",
                 kv_push_timeout_s: float = 10.0,
                 history_resolution_s: float = 5.0,
                 history_retention_s: float = 1800.0, slo_specs=None):
        assert role in ("prefill", "decode", "both"), role
        self.engine = engine
        self.host = host
        self.port = port
        self.max_inflight = len(engine.slots) + int(max_queue)
        # disaggregated prefill/decode (docs/serving.md): the replica's
        # advertised placement role — ADVISORY, any replica can serve any
        # request; the router's placement tiers read it off hello
        self.role = role
        self.kv_push_timeout_s = float(kv_push_timeout_s)
        # kv_push accounting (loop thread): outbound pushes attempted /
        # failed; page counts live on the engine/kv counters
        self._kv_pushes = 0
        self._kv_push_failures = 0
        # in-progress inbound multi-part kv_push blobs, keyed by
        # (conn.seq, client id) — dropped wholesale when the conn closes
        self._kv_parts: dict = {}
        # the server exports/dumps the ENGINE's tracer (the process-global
        # one unless the embedder gave the engine its own ring), so the
        # `trace` RPC snapshot, the metrics accounting, and the
        # postmortem spans all describe the same spans
        self.tracer = engine.tracer
        self.stats = StatSet("serving_server")
        # flight recorder (obs/flight.py): lifecycle events always record
        # while a server exists (they are per-request, not per-token);
        # postmortem BUNDLES are written only when a directory is
        # configured — on pump death, on the watchdog-wedge threshold
        # (pump_last_step_age_s > wedge_threshold_s), and on an operator
        # `dump` frame.
        self.flight = get_flight_recorder()
        self.flight.enabled = True
        self.postmortem_dir = postmortem_dir
        self._last_dump_error = "unknown"
        self.wedge_threshold_s = float(wedge_threshold_s)
        self._wedge_dumped = False    # one bundle per wedge episode
        self._last_beat_event = 0.0   # flight beats sampled at ~1/s
        self._inflight = 0            # accepted, not finished (loop thread)
        self._draining = False
        # pump heartbeat: (monotonic time, engine step count) written by
        # the pump once per loop iteration — a single tuple rebind, so any
        # thread reads it torn-free.  None until the pump first runs.
        self._pump_beat: Optional[tuple] = None
        self._conns: set = set()      # open connections (loop thread)
        self._routes: dict[str, _ReqState] = {}
        self._cmds: queue.Queue = queue.Queue()
        self._wake = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        self._pump_error: Optional[BaseException] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._idle: Optional[asyncio.Event] = None
        self._closed: Optional[asyncio.Event] = None
        self._crashed: Optional[asyncio.Event] = None
        self._watch_task = None       # the loop-side wedge watchdog
        self._bg_thread: Optional[threading.Thread] = None
        engine.on_token = self._on_token
        engine.on_finish = self._on_finish
        self._init_metrics()
        # the health plane (docs/observability.md "Health plane"): a
        # bounded time-series ring over the registry, fed by a background
        # sampler thread, with SLO burn-rate alerting riding each
        # sampling pass.  `slo_specs=None` takes the serving defaults;
        # pass () to disable alerting while keeping history.
        self.history = MetricHistory(self.metrics,
                                     resolution_s=history_resolution_s,
                                     retention_s=history_retention_s)
        self.metrics.register_collector(history_collector(self.history))
        self.slo = SloEvaluator(
            self.history,
            default_serving_slos() if slo_specs is None else slo_specs,
            flight=self.flight, registry=self.metrics,
            dump_fn=self._slo_dump)
        self.history_sampler = HistorySampler(self.history,
                                              on_sample=self.slo.evaluate)

    def _init_metrics(self) -> None:
        """The unified registry behind the `metrics` frame.  Rendered on
        the LOOP thread: engine-derived values are advisory stale-ok
        reads of pump-owned state (each individually GIL-atomic; the
        CONSISTENT view is the stats RPC's pump round trip) — that is
        what keeps metrics answerable while the pump is wedged, which is
        the whole point of the watchdog gauges."""
        reg = self.metrics = MetricsRegistry(strict=True)
        self._m_accepted = reg.counter("serving_requests_accepted_total")
        self._m_overload = reg.counter("serving_overload_total")
        # disaggregated prefill/decode: outbound kv_push attempts/failures
        # (loop thread increments, mirrored in self._kv_pushes for stats)
        self._m_kv_pushes = reg.counter("serving_kv_xfer_pushes_total")
        self._m_kv_push_fail = \
            reg.counter("serving_kv_xfer_push_failures_total")
        reg.gauge("serving_inflight").set_fn(lambda: float(self._inflight))
        reg.gauge("serving_max_inflight").set(float(self.max_inflight))
        reg.gauge("serving_draining").set_fn(
            lambda: 1.0 if self._draining else 0.0)
        reg.gauge("pump_alive").set_fn(
            lambda: 1.0 if self.pump_alive() else 0.0)
        reg.gauge("pump_last_step_age_s").set_fn(self.pump_last_step_age)
        eng = self.engine

        def engine_state():
            return [
                ("serving_queue_depth", "gauge", None,
                 float(len(eng.queue))),
                ("serving_slots_in_use", "gauge", None,
                 float(sum(1 for s in eng.slots if s is not None))),
                ("serving_num_slots", "gauge", None, float(len(eng.slots))),
                ("serving_pages_in_use", "gauge", None,
                 float(eng.kv.pages_in_use)),
                ("serving_free_pages", "gauge", None,
                 float(eng.kv.free_page_count)),
                ("serving_num_pages", "gauge", None,
                 float(eng.kv.num_pages)),
                ("serving_decode_steps_total", "counter", None,
                 float(eng.n_decode_steps)),
                ("serving_tokens_generated_total", "counter", None,
                 float(eng.tokens_generated)),
                ("serving_preemptions_total", "counter", None,
                 float(eng.n_preemptions)),
                ("serving_cancelled_total", "counter", None,
                 float(eng.n_cancelled)),
                ("serving_expired_total", "counter", None,
                 float(eng.n_expired)),
                # prefix caching: hit/miss/saved counters plus the
                # private/shared/cached page-accounting split
                ("serving_private_pages_in_use", "gauge", None,
                 float(eng.kv.private_pages_in_use)),
                ("serving_shared_pages_in_use", "gauge", None,
                 float(eng.kv.shared_pages_in_use)),
                ("serving_prefix_cached_pages", "gauge", None,
                 float(eng.kv.cached_page_count)),
                ("serving_prefix_nodes", "gauge", None,
                 float(eng.prefix.n_nodes if eng.prefix else 0)),
                ("serving_prefix_hits_total", "counter", None,
                 float(eng.n_prefix_hits)),
                ("serving_prefix_misses_total", "counter", None,
                 float(eng.n_prefix_misses)),
                ("serving_prefix_tokens_saved_total", "counter", None,
                 float(eng.prefill_tokens_saved)),
                ("serving_prefix_evictions_total", "counter", None,
                 float(eng.prefix.n_evictions if eng.prefix else 0)),
                ("serving_prefix_cow_total", "counter", None,
                 float(eng.kv.n_cow)),
                # KV spill tier: device->host spills, host->device
                # restores, and the host-RAM bytes currently resident
                # (bounded by spill_bytes_budget)
                ("serving_spill_pages_total", "counter", None,
                 float(eng.kv.n_spilled)),
                ("serving_restore_pages_total", "counter", None,
                 float(eng.kv.n_restored)),
                ("serving_spill_bytes", "gauge", None,
                 float(eng.kv.host_bytes)),
                # chunked prefill: mixed-step/chunk counters plus the
                # engine-owned token-budget histograms (step_tokens_hist /
                # decode_gap_hist keep their own locks; their samples()
                # splice straight into the frame)
                ("serving_prefill_chunks_total", "counter", None,
                 float(eng.n_prefill_chunks)),
                ("serving_mixed_steps_total", "counter", None,
                 float(eng.n_mixed_steps)),
                # multi-step decode: scan body iterations vs boundary
                # flushes — steps/flushes ≈ decode_steps in steady state
                ("serving_scan_steps_total", "counter", None,
                 float(eng.n_scan_steps)),
                ("serving_scan_flushes_total", "counter", None,
                 float(eng.n_scan_flushes)),
                # speculative decoding: drafted/accepted counters + the
                # lifetime accept rate (the throughput-multiplier dial)
                ("serving_spec_drafted_total", "counter", None,
                 float(eng.n_spec_drafted)),
                ("serving_spec_accepted_total", "counter", None,
                 float(eng.n_spec_accepted)),
                ("serving_spec_accept_rate", "gauge", None,
                 float(eng.spec_accept_rate)),
                # tensor-parallel sharded decode: shard count + per-device
                # pool residency (the HBM split sharding exists for)
                ("serving_tp_shards", "gauge", None, float(eng.tp)),
                ("serving_kv_pool_bytes_per_shard", "gauge", None,
                 float(eng.kv.pool_bytes_per_shard)),
                # speculative drafting: the drafter's host+device wall
                # per proposal pass and the per-slot chosen depth (the
                # dynamic-k policy's OUTPUT — an operator reads this
                # histogram to see whether the workload sustains depth)
                ("serving_draft_steps_total", "counter", None,
                 float(eng.n_draft_steps)),
                # cross-replica kv transfer: pages serialized to / scattered
                # from the wire, and blob mounts into the prefix tree
                ("serving_kv_xfer_pages_shipped_total", "counter", None,
                 float(eng.kv.n_exported)),
                ("serving_kv_xfer_pages_received_total", "counter", None,
                 float(eng.kv.n_imported)),
                ("serving_kv_xfer_mounts_total", "counter", None,
                 float(eng.n_kv_mounts)),
            ] + eng.step_tokens_hist.samples() \
              + eng.decode_gap_hist.samples() \
              + eng.draft_ms_hist.samples() \
              + eng.spec_k_hist.samples()

        reg.register_collector(engine_state)
        reg.register_collector(statset_collector(
            self.stats, "serving_latency_seconds", "serving_latency_count"))
        reg.register_collector(tracer_collector(self.tracer))
        # deep introspection: per-site jit compile counters (the recompile-
        # storm fuel), device-memory accounting (KV pool / param / live-
        # array bytes, CPU-safe), and flight-recorder ring accounting —
        # all render-time reads, nothing on the token hot path
        reg.register_collector(compile_collector())
        reg.register_collector(hbm_collector(
            params_fn=lambda: eng.params, kv_fn=lambda: eng.kv))
        reg.register_collector(flight_collector(self.flight))

    def pump_alive(self) -> bool:
        """False the moment the pump has fatally errored, even while its
        thread is still unwinding (recording the death, writing the
        bundle): `_pump_error` is written BEFORE the death is announced
        to the loop, so a client that just saw its routes failed must
        never read `pump_alive: true` in the next stats frame."""
        return (self._pump_error is None
                and self._pump_thread is not None
                and self._pump_thread.is_alive())

    def pump_last_step_age(self) -> float:
        """Seconds since the pump last completed a loop iteration; -1.0
        when it has not run yet.  Healthy: < ~0.6s even when idle (the
        idle wait is bounded at 0.5s).  Growing: the engine is wedged
        inside step() — visible here (and in the metrics frame) while
        generate streams merely stall."""
        beat = self._pump_beat
        if beat is None:
            return -1.0
        return time.monotonic() - beat[0]

    # -- lifecycle (asyncio side) -----------------------------------------
    async def start(self, start_pump: bool = True) -> tuple[str, int]:
        """Bind the listener (port 0 = ephemeral; self.port is updated to
        the bound port) and start the engine pump."""
        self._loop = asyncio.get_running_loop()
        self._idle = asyncio.Event()
        self._closed = asyncio.Event()
        self._crashed = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        # the wedge watchdog rides the LOOP thread (it must keep running
        # while the pump is stuck inside step()): past the threshold it
        # records a wedge event and freezes one postmortem bundle
        self._watch_task = self._loop.create_task(self._wedge_watchdog())
        # the health plane's sampler is a daemon thread like the pump: it
        # reads lock-guarded registry state, so it keeps the time-series
        # (and SLO evaluation) running while the pump is wedged
        self.history_sampler.start()
        if start_pump:
            self.start_pump()
        return self.host, self.port

    async def wait_crashed(self) -> None:
        """Resolves when the engine pump dies (tools/serve.py races this
        against its signal wait so a crashed server flushes its trace and
        exits nonzero instead of idling forever)."""
        await self._crashed.wait()

    def start_pump(self) -> None:
        """Start (or no-op if running) the engine pump thread.  Split from
        start() so tests can stage deterministic admission states before
        any scheduling happens."""
        if self._pump_thread is not None and self._pump_thread.is_alive():
            return
        self._pump_thread = threading.Thread(
            target=self._pump, name="serving-engine-pump", daemon=True)
        self._pump_thread.start()

    async def drain(self) -> None:
        """Graceful shutdown: stop admitting (new generates get an
        `overload/reason=draining` response), let every accepted request
        finish (deadlines still fire on schedule), then stop the pump and
        close the listener."""
        self._draining = True
        if self._inflight > 0:
            self._ensure_pump_for_inflight()
            self._idle.clear()
            await self._idle.wait()
        await self._shutdown()

    async def stop(self) -> None:
        """Hard shutdown: cancel everything in flight, then close."""
        self._draining = True
        for rid in list(self._routes):
            self._cmds.put(("cancel", rid))
        self._wake.set()
        if self._inflight > 0:
            self._ensure_pump_for_inflight()
            self._idle.clear()
            await self._idle.wait()
        await self._shutdown()

    def _ensure_pump_for_inflight(self) -> None:
        """Waiting on in-flight work with no pump running would wedge the
        drain forever (start_background(start_pump=False) is a public
        path).  Accepted work is drain's to finish — start the pump; a
        pump that DIED already failed every route via _pump_died_on_loop,
        so don't resurrect it."""
        if self._pump_error is None and (
                self._pump_thread is None or not self._pump_thread.is_alive()):
            self.start_pump()

    async def _shutdown(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        self.history_sampler.stop()
        if self._pump_thread is not None and self._pump_thread.is_alive():
            self._cmds.put(("stop",))
            self._wake.set()
            await asyncio.get_running_loop().run_in_executor(
                None, self._pump_thread.join)
        # TOCTOU sweep, mirroring _pump_died_on_loop: _handle_stats may
        # have seen the pump alive and enqueued AFTER the pump's own
        # stop-drain ran.  We are on the loop thread, so any such put
        # either already happened (visible here) or its _handle_stats
        # runs after this and sees the dead pump (stale fast path).
        try:
            while True:
                cmd = self._cmds.get_nowait()
                if cmd[0] == "stats":
                    self._stats_on_loop(cmd[1], None)
                elif cmd[0] == "kv_import":
                    cmd[2].send({"type": "kv_push", "id": cmd[1]["cid"],
                                 "ok": False, "error": "replica stopping"})
        except queue.Empty:
            pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # close every live connection EXPLICITLY: a client blocked on a
        # read must see EOF now, not hang until its socket timeout because
        # the loop died with the transport still open
        for conn in list(self._conns):
            conn.dead = True
            try:
                conn.writer.close()
            except (ConnectionError, RuntimeError):
                pass
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()

    # -- lifecycle (thread-facing wrappers) --------------------------------
    def start_background(self, start_pump: bool = True) -> tuple[str, int]:
        """Run the asyncio loop on a daemon thread; returns (host, port)
        once bound.  For embedders and tests — tools/serve.py runs the
        loop in the foreground instead."""
        started = threading.Event()
        addr: list = []

        async def _amain():
            addr.extend(await self.start(start_pump=start_pump))
            started.set()
            await self.wait_closed()

        self._bg_thread = threading.Thread(
            target=lambda: asyncio.run(_amain()),
            name="serving-server-loop", daemon=True)
        self._bg_thread.start()
        if not started.wait(timeout=60):
            raise RuntimeError("serving server failed to bind within 60s")
        return addr[0], addr[1]

    def stop_background(self, drain: bool = True, timeout: float = 120):
        """Drain (or hard-stop) a start_background() server and join its
        loop thread."""
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.drain() if drain else self.stop(), self._loop)
        fut.result(timeout=timeout)
        if self._bg_thread is not None:
            self._bg_thread.join(timeout=timeout)
        if self._pump_error is not None:
            raise RuntimeError("engine pump died") from self._pump_error

    # -- the engine pump (its own thread; sole owner of the engine) --------
    def _pump(self) -> None:
        try:
            while True:
                # heartbeat FIRST: written once per loop iteration, so a
                # wedge anywhere below (a hung compiled step, a stuck
                # host sync) freezes it and pump_last_step_age_s grows
                now = time.monotonic()
                self._pump_beat = (now, self.engine.n_decode_steps)
                if now - self._last_beat_event >= 1.0:
                    # SAMPLED into the flight ring (~1/s): a postmortem
                    # shows how recently, and at what step, the pump was
                    # demonstrably alive — without beats evicting the
                    # lifecycle events the ring exists for
                    self._last_beat_event = now
                    self.flight.record(
                        "pump_beat", step=self.engine.n_decode_steps,
                        queue_depth=len(self.engine.queue),
                        inflight=self._inflight)
                try:
                    while True:
                        cmd = self._cmds.get_nowait()
                        if cmd[0] == "stop":
                            # commands queued behind "stop" must not be
                            # orphaned: a consistent-stats client is
                            # blocking on its reply — answer it here (we
                            # ARE between steps on the pump thread, so
                            # the snapshot is consistent); _shutdown
                            # sweeps anything put after this drain
                            try:
                                while True:
                                    cmd = self._cmds.get_nowait()
                                    if cmd[0] == "stats":
                                        self._loop.call_soon_threadsafe(
                                            self._stats_on_loop, cmd[1],
                                            self._engine_stats())
                                    elif cmd[0] == "kv_import":
                                        self._loop.call_soon_threadsafe(
                                            cmd[2].send,
                                            {"type": "kv_push",
                                             "id": cmd[1]["cid"],
                                             "ok": False,
                                             "error": "replica stopping"})
                            except queue.Empty:
                                pass
                            return
                        if cmd[0] == "add":
                            req = cmd[1]
                            try:
                                self.engine.add_request(req)
                            except (ValueError, AssertionError) as e:
                                # validate() ran at admission, so only a
                                # race with a reconfigured engine lands
                                # here — still must answer the client
                                self._loop.call_soon_threadsafe(
                                    self._fail_on_loop, req.req_id, str(e))
                        elif cmd[0] == "cancel":
                            self.engine.cancel(cmd[1])
                        elif cmd[0] == "kv_import":
                            # between steps kv.pools is authoritative (the
                            # engine rebuilds its state pytree from it at
                            # every dispatch), so the mount's scatter is
                            # exactly as safe as an admission-time restore
                            push, conn = cmd[1], cmd[2]
                            try:
                                added = self.engine.import_prefix(
                                    push["tokens"], push["meta"],
                                    b"".join(push["parts"]))
                                reply = {"type": "kv_push",
                                         "id": push["cid"], "ok": True,
                                         "pages": int(push["meta"]
                                                      ["n_pages"]),
                                         "mounted": int(added)}
                            except (ValueError, AssertionError) as e:
                                reply = {"type": "kv_push",
                                         "id": push["cid"], "ok": False,
                                         "error": f"{type(e).__name__}: "
                                                  f"{e}"}
                            self._loop.call_soon_threadsafe(
                                conn.send, reply)
                        elif cmd[0] == "stats":
                            # between-steps = the consistent view: no
                            # slot/page/queue mutation can interleave
                            self._loop.call_soon_threadsafe(
                                self._stats_on_loop, cmd[1],
                                self._engine_stats())
                except queue.Empty:
                    pass
                busy = self.engine.step()
                if not busy:
                    # idle: nothing queued or in flight — sleep until a
                    # command arrives (bounded wait as a safety net)
                    self._wake.wait(timeout=0.5)
                    self._wake.clear()
        except BaseException as e:                     # noqa: BLE001
            self._pump_error = e
            # the black-box moment: the pump thread is dying with the
            # engine state frozen exactly as the failure left it — record
            # the death and freeze one bundle HERE, before the loop-side
            # cleanup mutates anything (routes, inflight)
            import traceback

            err = f"{type(e).__name__}: {e}"
            self.flight.record("pump_death", error=err)
            self._write_bundle("pump_death",
                               error=err + "\n" + traceback.format_exc())
            if self._loop is not None:
                self._loop.call_soon_threadsafe(self._pump_died_on_loop)

    def _pump_died_on_loop(self) -> None:
        """A dead pump strands every accepted request — fail them all so
        no client hangs on a stream that will never finish.  Pending
        consistent-stats round trips must answer too (stale): draining
        them HERE, on the loop thread, closes the TOCTOU where
        _handle_stats checks pump health, the pump dies and drains, and
        only then does the command land in the queue — any such late put
        happens on this thread, so it is either already in the queue now
        or its _handle_stats saw _pump_error set (the pump writes it
        before scheduling this callback) and took the stale path."""
        try:
            while True:
                cmd = self._cmds.get_nowait()     # nobody else reads now
                if cmd[0] == "stats":
                    self._stats_on_loop(cmd[1], None)
                elif cmd[0] == "kv_import":
                    cmd[2].send({"type": "kv_push", "id": cmd[1]["cid"],
                                 "ok": False, "error": "engine pump died"})
        except queue.Empty:
            pass
        for rid in list(self._routes):
            self._fail_on_loop(rid, f"engine pump died: "
                                    f"{type(self._pump_error).__name__}: "
                                    f"{self._pump_error}")
        if self._crashed is not None:
            self._crashed.set()

    # -- the flight recorder / postmortem bundles --------------------------
    async def _wedge_watchdog(self) -> None:
        """Loop-side wedge detector: when the pump is ALIVE but its beat
        age crosses `wedge_threshold_s`, record a wedge event and freeze
        one postmortem bundle (engine reads are stale-ok — the pump is
        stuck, not racing).  Re-arms when the beat recovers, so a flapping
        engine produces one bundle per episode, not one per poll."""
        period = max(0.05, min(1.0, self.wedge_threshold_s / 4.0))
        while True:
            await asyncio.sleep(period)
            age = self.pump_last_step_age()
            # pump_alive() is False once _pump_error is set: a DEAD pump
            # already froze its own pump_death bundle — the watchdog must
            # not stack a wedge bundle on top of it
            if self.pump_alive() and age > self.wedge_threshold_s:
                if not self._wedge_dumped:
                    self._wedge_dumped = True
                    self.flight.record("wedge", age_s=round(age, 3),
                                       step=(self._pump_beat or (0, -1))[1])
                    self._write_bundle(
                        "wedge", error=f"pump wedged: last beat "
                                       f"{age:.1f}s ago "
                                       f"(threshold "
                                       f"{self.wedge_threshold_s:g}s)")
            elif age >= 0.0 and age <= self.wedge_threshold_s:
                self._wedge_dumped = False

    def _engine_snapshot(self) -> dict:
        """Engine state for a bundle: per-slot occupancy, queued request
        ids, pool accounting.  Stale-ok reads from whatever thread dumps
        (the pump is dead or wedged in every trigger path); a racing
        mutation degrades one field to an error string, never the dump."""
        eng = self.engine

        def _safe(fn):
            try:
                return fn()
            except Exception as e:             # noqa: BLE001 — see above
                return f"snapshot_error: {type(e).__name__}: {e}"

        return {
            "slots": _safe(lambda: [
                None if sl is None else {
                    "slot": i, "req_id": str(sl.req.req_id),
                    "pos": int(sl.pos), "generated": int(sl.gen),
                    "max_new": int(sl.req.max_new),
                    "replay_until": int(sl.replay_until),
                } for i, sl in enumerate(list(eng.slots))]),
            "queued": _safe(lambda: [str(r.req_id)
                                     for r in list(eng.queue)]),
            "inflight_routes": _safe(lambda: [str(r)
                                              for r in list(self._routes)]),
            "pages_in_use": _safe(lambda: int(eng.kv.pages_in_use)),
            "free_pages": _safe(lambda: int(eng.kv.free_page_count)),
            "num_pages": int(eng.kv.num_pages),
            "page_size": int(eng.kv.page_size),
            "num_slots": len(eng.slots),
            "tp_shards": int(eng.tp),
            "kv_pool_bytes_per_shard": _safe(
                lambda: int(eng.kv.pool_bytes_per_shard)),
            "n_decode_steps": eng.n_decode_steps,
            "tokens_generated": eng.tokens_generated,
            "n_preemptions": eng.n_preemptions,
            "n_cancelled": eng.n_cancelled,
            "n_expired": eng.n_expired,
            "speculation": _safe(lambda: {
                "spec_k": eng.spec_k,
                "drafter": eng.drafter_kind,
                "dynamic": bool(eng.spec_dynamic),
                "draft_steps": eng.n_draft_steps,
                "steps": eng.n_spec_steps,
                "chains": eng.n_spec_chains,
                "drafted": eng.n_spec_drafted,
                "accepted": eng.n_spec_accepted,
                "tokens": eng.n_spec_tokens,
                "accept_rate": round(eng.spec_accept_rate, 4),
                # per-slot dynamic-k state: the learned accept EWMA each
                # live slot steers its draft depth by (null = cold/idle)
                "slot_accept_ewma": [
                    None if sl is None or sl.accept_ewma is None
                    else round(float(sl.accept_ewma), 4)
                    for sl in eng.slots],
            }),
            "prefix_cache": _safe(lambda: {
                "enabled": eng.prefix is not None,
                "nodes": eng.prefix.n_nodes if eng.prefix else 0,
                "cached_pages": int(eng.kv.cached_page_count),
                "shared_pages_in_use": int(eng.kv.shared_pages_in_use),
                "private_pages_in_use": int(eng.kv.private_pages_in_use),
                "hits": eng.n_prefix_hits,
                "misses": eng.n_prefix_misses,
                "tokens_saved": eng.prefill_tokens_saved,
                "evictions": eng.prefix.n_evictions if eng.prefix else 0,
                "cow": int(eng.kv.n_cow),
                # KV spill tier (docs/serving.md): host-resident pages/
                # bytes + the spill/restore lifecycle counters
                "spill_bytes_budget": int(eng.kv.spill_bytes_budget),
                "host_pages": int(eng.kv.host_page_count),
                "spill_bytes": int(eng.kv.host_bytes),
                "spilled_pages": int(eng.kv.n_spilled),
                "restored_pages": int(eng.kv.n_restored),
                "host_evicted_pages": int(eng.kv.n_host_evicted),
                "restore_hits": eng.n_restore_hits,
                "restore_tokens_saved": eng.restore_tokens_saved,
            }),
            "compile_watch": get_compile_watch().snapshot(),
            "hbm": hbm_snapshot(params=eng.params, kv=eng.kv),
        }

    def _config_snapshot(self) -> dict:
        return {
            "host": self.host, "port": self.port,
            "max_inflight": self.max_inflight,
            "num_slots": len(self.engine.slots),
            "page_size": int(self.engine.kv.page_size),
            "num_pages": int(self.engine.kv.num_pages),
            "capacity_tokens": int(self.engine.kv.capacity_tokens),
            "prefix_cache": self.engine.prefix is not None,
            "spill_bytes_budget": int(self.engine.kv.spill_bytes_budget),
            "tp_shards": int(self.engine.tp),
            "spec_k": int(self.engine.spec_k),
            "spec_dynamic": bool(self.engine.spec_dynamic),
            "drafter": self.engine.drafter_kind,
            "decode_steps": int(self.engine.decode_steps),
            "decode_mode": self.engine.decode_mode,
            "role": self.role,
            "wedge_threshold_s": self.wedge_threshold_s,
            "postmortem_dir": self.postmortem_dir,
        }

    def _slo_dump(self, fired) -> None:
        """The SLO evaluator's episode hook (sampler thread): freeze the
        bundle with the offending series attached while the pump is
        still ALIVE — the proactive counterpart of the wedge dump, same
        stale-ok snapshot paths."""
        names = ",".join(sorted({str(f.get("slo", "?")) for f in fired}))
        self._write_bundle(f"slo:{names}", error=f"slo firing: {names}")

    def _write_bundle(self, reason: str,
                      error: Optional[str] = None) -> Optional[str]:
        """Freeze one postmortem bundle; returns its path, or None when no
        directory is configured or the dump itself failed (a broken dump
        must never mask the failure being documented)."""
        if not self.postmortem_dir:
            return None
        try:
            path = self.flight.dump(
                self.postmortem_dir, reason,
                spans=self.tracer.snapshot(),
                engine=self._engine_snapshot(),
                metrics=self.metrics.snapshot(),
                config=self._config_snapshot(),
                history=self.history.snapshot(),
                error=error)
            print(f"postmortem bundle ({reason}): {path}", file=sys.stderr,
                  flush=True)
            return path
        except Exception as e:                 # noqa: BLE001
            self._last_dump_error = f"{type(e).__name__}: {e}"
            print(f"postmortem dump failed ({reason}): "
                  f"{self._last_dump_error}", file=sys.stderr, flush=True)
            return None

    # -- engine hooks (pump thread) ----------------------------------------
    def _on_token(self, rid: str, tok: int, idx: int) -> None:
        st = self._routes.get(rid)
        if st is None:
            return
        now = time.monotonic()
        # burst bookkeeping counts EVERY banked token (replays included —
        # within one burst replayed indexes precede fresh ones), so the
        # position within the engine's current ≤k-token burst is exact
        if st.burst_left > 0:
            st.burst_left -= 1
        else:                                  # first token of a new burst
            st.burst_left = max(1, int(self.engine.cur_burst)) - 1
            st.burst_share = -1.0
        if idx >= st.next_idx:                 # fresh, not a preempt replay
            if idx == 0:
                self.stats.get("first_token_latency").add(now - st.t_submit)
            else:
                if st.burst_share < 0.0:
                    # first FRESH token since t_last: the gap since then
                    # covers this token and the burst_left still to come
                    # (all fresh — replays sort first), so each owns an
                    # equal share.  At decode_steps=1 the burst is one
                    # token and this is the classic per-token charge;
                    # at k>1 this keeps token_latency percentiles
                    # comparable across decode_steps settings.
                    st.burst_share = (now - st.t_last) / (st.burst_left + 1)
                self.stats.get("token_latency").add(st.burst_share)
            # t_last advances on FRESH tokens only: replayed (deduped)
            # emissions reach no client, so the first post-replay fresh
            # token must charge the whole preempt+re-prefill+replay stall
            # to token_latency — that stall is exactly what the stats
            # RPC's p99 exists to expose
            st.t_last = now
            st.next_idx = idx + 1
            if st.stream:
                self._loop.call_soon_threadsafe(
                    st.conn.send, {"type": "token", "id": st.cid,
                                   "token": int(tok), "index": int(idx),
                                   "burst": st.burst_left + 1})

    def _on_finish(self, rid: str, toks: np.ndarray, reason: str) -> None:
        # the server owns delivery — keep the engine's archive empty so a
        # long-lived process holds no unbounded result map
        self.engine.results.pop(rid, None)
        self.engine.finish_reasons.pop(rid, None)
        timing = self.engine.finish_timing.pop(rid, None)
        st = self._routes.get(rid)
        if st is None:
            return
        wall = time.monotonic() - st.t_submit
        self.stats.get("request_latency").add(wall)
        if timing is not None:
            # the server-observed request wall time (accept -> finish)
            # rides next to the engine-phase sum: the gap between them is
            # command-queue/pump-pickup latency, and the gap between
            # request_ms and the CLIENT's wall time is the wire + front
            # tier — per-hop attribution with no trace viewer needed
            timing["request_ms"] = round(wall * 1e3, 3)
        if st.push_to is not None and reason in ("stop", "length"):
            # disaggregated prefill: the prompt's pages were just donated
            # (_retire runs _donate before _finish), so the committed
            # prefix is exportable RIGHT HERE on the pump thread; the
            # loop side then ships it and delays the done frame until the
            # push resolves, so the router learns push_ok from `done`.
            # A cancelled/expired prefill finishes NORMALLY — shipping
            # pages nobody will decode would only burn wire and counters.
            export = self.engine.export_prefix(st.prompt)
            self._loop.call_soon_threadsafe(
                self._push_then_finish_on_loop, rid,
                np.asarray(toks).astype(int).tolist(), reason, timing,
                export)
            return
        self._loop.call_soon_threadsafe(
            self._finish_on_loop, rid,
            np.asarray(toks).astype(int).tolist(), reason, timing)

    # -- loop-side completion/error delivery -------------------------------
    def _finish_on_loop(self, rid: str, tokens: list, reason: str,
                        timing: Optional[dict] = None,
                        extra: Optional[dict] = None) -> None:
        st = self._routes.pop(rid, None)
        if st is None:
            return
        st.conn.rids.pop(st.cid, None)
        # accounting settles BEFORE the terminal frame can reach the
        # client: asyncio flushes small writes inside send(), so a client
        # acting on `done` (e.g. polling stats, or a test asserting
        # inflight) must never observe the request still counted
        self._dec_inflight()
        out = {"type": "done", "id": st.cid, "tokens": tokens,
               "reason": reason}
        if timing is not None:
            out["timing"] = timing
        if extra:
            out.update(extra)
        st.conn.send(out)

    # -- the kv_push sender (prefill side, loop thread) --------------------
    def _push_then_finish_on_loop(self, rid: str, tokens: list, reason: str,
                                  timing: Optional[dict],
                                  export) -> None:
        """Ship a finished prefill_only request's committed prefix to its
        decode replica, then deliver the (delayed) done frame carrying
        the push outcome.  Every failure mode — nothing cached, connect
        refused, peer error, timeout — degrades to push_ok:false on the
        done frame; the ROUTER owns the fallback placement."""
        st = self._routes.get(rid)
        if st is None:
            return

        async def _run():
            ok, err, pages, nbytes = False, "nothing cached to ship", 0, 0
            if export is not None:
                xtoks, meta, payload = export
                pages, nbytes = int(meta["n_pages"]), len(payload)
                try:
                    ok, err = await asyncio.wait_for(
                        self._kv_push(st.push_to, st.cid, xtoks, meta,
                                      payload),
                        timeout=self.kv_push_timeout_s)
                except asyncio.TimeoutError:
                    ok, err = False, f"kv_push timed out after " \
                                     f"{self.kv_push_timeout_s:g}s"
                except (OSError, wire.FrameError) as e:
                    # FrameError: the peer closed mid-frame or replied
                    # malformed/over-cap — same degradation as a socket
                    # error, NOT a task-killing exception
                    ok, err = False, f"kv_push failed: {e}"
                except Exception as e:       # noqa: BLE001 — this task is
                    # fire-and-forget: an exception escaping here would
                    # swallow the done frame (the router's prefill leg
                    # hangs with no retry), leak the route, and pin an
                    # inflight slot forever; ANY failure must degrade to
                    # push_ok:false so _finish_on_loop always runs
                    ok, err = False, f"kv_push failed: " \
                                     f"{type(e).__name__}: {e}"
            self._kv_pushes += 1
            self._m_kv_pushes.inc()
            if ok:
                self.flight.record(
                    "kv_ship", pages=pages, bytes=nbytes,
                    dest=f"{st.push_to.get('host')}:"
                         f"{st.push_to.get('port')}")
            else:
                self._kv_push_failures += 1
                self._m_kv_push_fail.inc()
            extra = {"push_ok": ok, "pushed_pages": pages if ok else 0}
            if not ok:
                extra["push_error"] = err
            self._finish_on_loop(rid, tokens, reason, timing, extra=extra)

        self._loop.create_task(_run())

    async def _kv_push(self, push_to: dict, cid, toks, meta: dict,
                       payload: bytes) -> tuple[bool, str]:
        """One outbound kv_push: connect to the decode replica, stream
        the blob as BIN frames chunked under the serving binary-frame cap
        (part 0 carries tokens + meta; the receiver mounts on `last`),
        await the single kv_push reply.  The caller bounds the whole
        exchange with kv_push_timeout_s."""
        frames = _kv_push_frames(cid, toks, meta, payload)
        reader, writer = await asyncio.open_connection(
            str(push_to.get("host")), int(push_to.get("port")))
        try:
            for frame in frames:
                writer.write(frame)
                await writer.drain()
            while True:
                reply = await wire.read_frame(
                    reader, bin_cap=wire.MAX_BIN_PAYLOAD)
                if reply is None:
                    return False, "peer closed during kv_push"
                if reply.get("type") == "kv_push":
                    return (bool(reply.get("ok")),
                            str(reply.get("error", "")))
                if reply.get("type") == "error":
                    return False, str(reply.get("error"))
        finally:
            try:
                writer.close()
            except ConnectionError:
                pass

    def _fail_on_loop(self, rid: str, message: str) -> None:
        st = self._routes.pop(rid, None)
        if st is None:
            return
        st.conn.rids.pop(st.cid, None)
        self._dec_inflight()
        st.conn.send({"type": "error", "id": st.cid, "error": message})

    def _dec_inflight(self) -> None:
        self._inflight -= 1
        if self._inflight == 0 and self._idle is not None:
            self._idle.set()

    # -- connection handling (asyncio side) --------------------------------
    async def _handle(self, reader, writer) -> None:
        conn = _Conn(writer)
        self._conns.add(conn)
        first_frame = True
        try:
            while True:
                try:
                    msg = await wire.read_frame(
                        reader, bin_cap=wire.MAX_BIN_PAYLOAD)
                except wire.FrameError as e:
                    # a malformed FIRST frame is usually a peer speaking the
                    # wrong protocol entirely (an HTTP probe, a bare JSON
                    # line) — name what this socket expects instead of a
                    # bare parse error, so the peer (and the fleet router's
                    # classification path) learns what it reached
                    err = str(e)
                    if first_frame:
                        err += f"; expected the {wire.PROTO_DESC}"
                    conn.send({"type": "error", "error": err})
                    break
                if msg is None:
                    break
                first_frame = False
                try:
                    self._dispatch(conn, msg)
                except Exception as e:         # noqa: BLE001 — protocol
                    # garbage (e.g. an unhashable JSON id) must answer an
                    # error frame, not tear down the connection and every
                    # other request multiplexed on it
                    bad_id = msg.get("id")
                    conn.send({"type": "error",
                               "id": bad_id if isinstance(bad_id, (str, int))
                               else None,
                               "error": f"bad {msg.get('type')!r} frame: "
                                        f"{type(e).__name__}: {e}"})
                try:
                    await writer.drain()
                except ConnectionError:
                    break
        finally:
            conn.dead = True
            self._conns.discard(conn)
            # client went away: everything it still has in flight is a
            # client-initiated cancel — slots and pages must not stay
            # pinned to a dead socket
            for rid in list(conn.rids.values()):
                self._cmds.put(("cancel", rid))
            # half-shipped kv_push blobs die with their connection — the
            # buffered parts must not outlive the peer that was sending
            for key in [k for k in self._kv_parts if k[0] == conn.seq]:
                del self._kv_parts[key]
            self._wake.set()
            try:
                writer.close()
            except ConnectionError:
                pass

    def _dispatch(self, conn: _Conn, msg: dict) -> None:
        t = msg.get("type")
        if t == "generate":
            self._handle_generate(conn, msg)
        elif t == "kv_push":
            self._handle_kv_push(conn, msg)
        elif t == "cancel":
            cid = msg.get("id")
            rid = conn.rids.get(cid) if isinstance(cid, (str, int)) else None
            if rid is not None:
                self._cmds.put(("cancel", rid))
                self._wake.set()
            # unknown/already-finished id: the done frame already answered
        elif t == "stats":
            self._handle_stats(conn, msg)
        elif t == "metrics":
            # answered on the LOOP thread on purpose: the Prometheus view
            # (incl. pump_last_step_age_s) must stay readable while the
            # pump is wedged — engine-derived values are stale-ok reads
            conn.send({"type": "metrics", "text": self.metrics.render(),
                       "content_type": "text/plain; version=0.0.4"})
        elif t == "dump":
            # operator-initiated postmortem: freeze a bundle NOW (loop
            # thread, stale-ok engine reads — works against a wedged or
            # dead pump, which is exactly when an operator wants one)
            self.flight.record("dump_rpc")
            if not self.postmortem_dir:
                conn.send({"type": "error", "id": msg.get("id"),
                           "error": "no postmortem dir configured "
                                    "(ServingServer(postmortem_dir=...) / "
                                    "tools/serve.py --postmortem-dir)"})
                return
            path = self._write_bundle("rpc")
            if path is None:
                # configured but the dump itself failed (disk full, bad
                # permissions, ...) — tell the operator the REAL cause,
                # not "go configure the directory you already configured"
                conn.send({"type": "error", "id": msg.get("id"),
                           "error": f"postmortem dump failed: "
                                    f"{self._last_dump_error}"})
            else:
                conn.send({"type": "dump", "id": msg.get("id"),
                           "path": path,
                           "events": self.flight.recorded,
                           "spans": self.tracer.recorded})
        elif t == "trace":
            # trace collection over the wire (loop thread, stale-ok like
            # `metrics` — snapshot() is safe concurrent with the pump, so
            # this answers even against a wedged engine): the retained
            # span ring plus the process identity a merger needs to put
            # these spans on their own track group, and a perf_counter
            # sample for ping-RTT clock alignment (the span timebase is
            # THIS process's perf_counter epoch).  `enable` flips tracing
            # LIVE (no restart — the operator's "start tracing NOW on the
            # misbehaving replica" move, and the bench overhead probe's
            # same-fleet A/B switch); the flip applies before the
            # snapshot, so enable:false returns the spans it just froze.
            conn.send(trace_reply(self.tracer, msg, "replica",
                                  self.host, self.port))
        elif t == "history":
            # the health plane's time-series pull (loop thread, stale-ok
            # like `metrics`/`trace`: the ring is fed by its own sampler
            # thread and read here under its lock — no pump round trip,
            # no shared lock with any other reply type — so it answers
            # against a wedged pump; staleness shows as last_sample_unix)
            conn.send(history_reply(self.history, msg, "replica",
                                    self.host, self.port))
        elif t == "hello":
            # version/capabilities negotiation: answered on connect so a
            # peer (the fleet router, a ctl, a probing operator) can
            # classify this end before sending work at it.  `page_size`
            # rides along because the router's prefix-affinity index keys
            # on the first page_size-aligned token run — the granularity
            # must match the replica's prefix tree for affinity to pay.
            conn.send(wire.hello_msg(
                "replica",
                server="paddle_tpu-serving",
                capabilities=sorted(["hello", "generate", "cancel", "stats",
                                     "metrics", "dump", "ping", "trace",
                                     "history", "kv_xfer"]),
                role_mode=self.role,
                num_slots=len(self.engine.slots),
                max_inflight=self.max_inflight,
                page_size=int(self.engine.kv.page_size),
                prefix_cache=self.engine.prefix is not None,
                tp_shards=int(self.engine.tp),
                spec_k=int(self.engine.spec_k),
                spec_dynamic=bool(self.engine.spec_dynamic),
                drafter=self.engine.drafter_kind,
                draining=self._draining))
        elif t == "ping":
            conn.send({"type": "pong"})
        else:
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": f"unknown message type {t!r}"})

    def _handle_kv_push(self, conn: _Conn, msg: dict) -> None:
        """Inbound cross-replica KV blob (decode side).  Multi-part BIN
        frames accumulate per (connection, id) — part 0 carries tokens +
        meta and declares the page count, later parts append payload
        bytes, `last` hands the whole blob to the pump for an
        import_prefix mount between steps.  Buffering is bounded twice:
        each accumulation by its DECLARED blob (itself bounded by the
        receiver's own pool size), and the SUM of declared blobs across
        all live accumulations by one pool's worth of bytes — so a peer
        opening many connections (or interleaving many ids) cannot
        buffer multiples of the pool in host RAM.  A sender that
        overruns its declaration, skips part 0, or repeats part 0 for a
        live id is refused immediately — never buffered unboundedly."""
        cid = msg.get("id")
        if not isinstance(cid, (str, int)):
            conn.send({"type": "error", "id": None,
                       "error": "kv_push needs a string or int 'id'"})
            return
        key = (conn.seq, cid)

        def refuse(err: str) -> None:
            self._kv_parts.pop(key, None)
            conn.send({"type": "kv_push", "id": cid, "ok": False,
                       "error": err})

        if self.engine.prefix is None:
            refuse("prefix cache disabled on this replica")
            return
        if self._draining:
            refuse("replica is draining")
            return
        if not self.pump_alive():
            refuse("engine pump is not running")
            return
        payload = msg.get(wire.PAYLOAD_KEY) or b""
        if int(msg.get("seq", 0)) == 0:
            if key in self._kv_parts:
                # a repeated part 0 means the sender's stream is confused
                # — refuse (dropping the half-built blob) rather than
                # silently restarting the accumulation mid-flight
                refuse(f"kv_push part 0 repeated for id {cid!r} while "
                       f"its blob is still accumulating")
                return
            meta = msg.get("meta") or {}
            n = int(meta.get("n_pages", 0))
            if n <= 0 or n >= self.engine.kv.num_pages:
                refuse(f"blob declares {n} pages; this replica's pool "
                       f"holds {self.engine.kv.num_pages}")
                return
            expect = n * self.engine.kv.page_nbytes
            # server-wide budget: total DECLARED bytes across every live
            # accumulation stays under one pool's worth — any single
            # blob fits (it declares < num_pages), so only concurrent
            # pushes that could never all mount anyway are refused
            pending = sum(s["expect"] for s in self._kv_parts.values())
            budget = self.engine.kv.num_pages * self.engine.kv.page_nbytes
            if pending + expect > budget:
                refuse(f"kv_push buffer budget exhausted: {pending} "
                       f"bytes already accumulating, blob declares "
                       f"{expect} more, budget is {budget}")
                return
            self._kv_parts[key] = {
                "cid": cid, "tokens": msg.get("tokens") or [],
                "meta": meta, "parts": [], "bytes": 0,
                "expect": expect}
        st = self._kv_parts.get(key)
        if st is None:
            refuse("kv_push part arrived with no part 0")
            return
        st["parts"].append(payload)
        st["bytes"] += len(payload)
        if st["bytes"] > st["expect"]:
            refuse(f"kv_push accumulated {st['bytes']} bytes, over the "
                   f"{st['expect']}-byte declared blob")
            return
        if msg.get("last"):
            self._kv_parts.pop(key, None)
            self._cmds.put(("kv_import", st, conn))
            self._wake.set()

    def _handle_generate(self, conn: _Conn, msg: dict) -> None:
        cid = msg.get("id")
        if not isinstance(cid, (str, int)):
            # echo whatever id the client sent (it came off the wire, so it
            # is JSON-serializable) — an id-less error frame could never be
            # routed by the client and would stall its collect()
            conn.send({"type": "error", "id": cid,
                       "error": "generate needs a string or int 'id'"})
            return
        if cid in conn.rids:
            conn.send({"type": "error", "id": cid,
                       "error": f"id {cid!r} is already in flight on this "
                                f"connection"})
            return
        if self._pump_error is not None:
            # a dead pump can never serve this — fail fast instead of
            # letting the client block on frames that will never come
            conn.send({"type": "error", "id": cid,
                       "error": f"engine pump died: "
                                f"{type(self._pump_error).__name__}: "
                                f"{self._pump_error}"})
            return
        if self._draining:
            self._m_overload.inc()
            self.flight.record("overload", reason="draining")
            conn.send({"type": "overload", "id": cid, "reason": "draining"})
            return
        if self._inflight >= self.max_inflight:
            # the explicit backpressure contract: never queue unboundedly
            self._m_overload.inc()
            self.flight.record("overload", reason="queue_full",
                               inflight=self._inflight)
            conn.send({"type": "overload", "id": cid, "reason": "queue_full",
                       "inflight": self._inflight,
                       "max_inflight": self.max_inflight})
            return
        if msg.get("prefill_only"):
            # disaggregated prefill: run the prompt through admission
            # (prefill + donation) but generate nothing beyond the one
            # token sampling requires — the router discards it; streaming
            # is forced off so the decode replica's run owns every token
            msg = dict(msg, max_new=1, stream=False)
        try:
            req = self._build_request(conn, cid, msg)
            self.engine.validate(req)
        except (ValueError, AssertionError, TypeError) as e:
            conn.send({"type": "error", "id": cid, "error": str(e)})
            return
        st = _ReqState(conn, cid, msg.get("stream", True))
        if msg.get("prefill_only"):
            push_to = msg.get("push_to")
            st.push_to = push_to if isinstance(push_to, dict) else None
            st.prompt = req.prompt_ids
        self._routes[req.req_id] = st
        conn.rids[cid] = req.req_id
        self._inflight += 1
        self._m_accepted.inc()
        self.flight.record("accept", req=str(req.req_id),
                           inflight=self._inflight)
        self._cmds.put(("add", req))
        self._wake.set()

    def _build_request(self, conn: _Conn, cid, msg: dict) -> Request:
        prompt = np.asarray(msg.get("prompt", []), np.int32)
        rng = None
        if msg.get("seed") is not None:
            import jax

            rng = jax.random.PRNGKey(int(msg["seed"]))
        deadline = None
        if msg.get("timeout_s") is not None:
            # absolute on the ENGINE clock — the deadline sweep in step()
            # compares against engine.clock(), not the server's wall clock
            deadline = self.engine.clock() + float(msg["timeout_s"])
        # distributed-trace context: a router (or a tracing client)
        # stamps {"trace": {"trace_id", "parent"?}} on the generate frame;
        # adopting it here is what joins the engine's lifecycle spans to
        # the sender's trace (wire.get_trace drops malformed contexts —
        # shared with the pserver's send_grad/barrier adoption).
        trace = wire.get_trace(msg)
        # engine req_ids are namespaced per connection so two clients
        # picking "0" can never collide inside the scheduler; the type tag
        # keeps JSON id 1 and id "1" distinct too (conn.rids already does)
        tag = "i" if isinstance(cid, int) else "s"
        return Request(f"c{conn.seq}:{tag}:{cid}", prompt,
                       max_new=int(msg.get("max_new", 32)),
                       temperature=float(msg.get("temperature", 0.0)),
                       top_k=int(msg.get("top_k", 0)),
                       top_p=float(msg.get("top_p", 0.0)),
                       eos_id=int(msg.get("eos_id", -1)),
                       rng=rng, deadline=deadline, trace=trace)

    def _handle_stats(self, conn: _Conn, msg: dict) -> None:
        """Default path: the engine-state half of the snapshot is built
        BETWEEN STEPS on the pump thread (command-queue round trip), so
        `slots_in_use`/`pages_in_use`/`queue_depth` can never tear across
        a step boundary.  `{"stale_ok": true}` (or a pump that is dead /
        never started) answers immediately from the loop thread with
        GIL-atomic-but-unsynchronized reads — the watchdog's fast path,
        which must not block behind a wedged or absent pump."""
        if msg.get("stale_ok") or not self.pump_alive():
            conn.send(self._stats_msg(engine_part=None))
            return
        self._cmds.put(("stats", conn))
        self._wake.set()

    def _stats_on_loop(self, conn: _Conn, engine_part: Optional[dict]):
        conn.send(self._stats_msg(engine_part=engine_part))

    def _engine_stats(self) -> dict:
        """The engine-owned snapshot half.  Mutually consistent ONLY when
        called on the pump thread between steps; the stale fast path
        calls it from the loop thread and labels the result."""
        eng = self.engine
        return {
            "queue_depth": len(eng.queue),
            "slots_in_use": sum(1 for s in eng.slots if s is not None),
            "num_slots": len(eng.slots),
            "pages_in_use": int(eng.kv.pages_in_use),
            "free_pages": int(eng.kv.free_page_count),
            "num_pages": int(eng.kv.num_pages),
            "decode_steps": eng.n_decode_steps,
            "tokens_generated": eng.tokens_generated,
            "preemptions": eng.n_preemptions,
            "cancelled": eng.n_cancelled,
            "expired": eng.n_expired,
            "prefix_hits": eng.n_prefix_hits,
            "prefix_misses": eng.n_prefix_misses,
            "prefix_tokens_saved": eng.prefill_tokens_saved,
            "prefix_cached_pages": int(eng.kv.cached_page_count),
            "prefix_evictions": (eng.prefix.n_evictions
                                 if eng.prefix else 0),
            # host spill tier: pages parked in host RAM + restore traffic
            "spill_pages": int(eng.kv.host_page_count),
            "spill_bytes": int(eng.kv.host_bytes),
            "spilled_pages_total": int(eng.kv.n_spilled),
            "restored_pages_total": int(eng.kv.n_restored),
            "restore_hits": eng.n_restore_hits,
            "restore_tokens_saved": eng.restore_tokens_saved,
            # cross-replica kv transfer (the disagg plane's operator view)
            "kv_pages_shipped": int(eng.kv.n_exported),
            "kv_pages_received": int(eng.kv.n_imported),
            "kv_mounts": eng.n_kv_mounts,
            "prefill_chunk": eng.prefill_chunk,
            "max_step_tokens": eng.max_step_tokens,
            "prefill_chunks": eng.n_prefill_chunks,
            "mixed_steps": eng.n_mixed_steps,
            # speculative decoding: the A/B-able knobs + the counters the
            # accept rate reconciles from, plus the adaptive state
            # (drafter kind, dynamic-k flag, per-slot learned EWMAs)
            "spec_k": eng.spec_k,
            "spec_drafter": eng.drafter_kind,
            "spec_dynamic": bool(eng.spec_dynamic),
            "spec_draft_steps": eng.n_draft_steps,
            "spec_drafted": eng.n_spec_drafted,
            "spec_accepted": eng.n_spec_accepted,
            "spec_accept_rate": round(eng.spec_accept_rate, 4),
            "spec_slot_accept_ewma": [
                None if sl is None or sl.accept_ewma is None
                else round(float(sl.accept_ewma), 4)
                for sl in eng.slots],
            # multi-step decode: the A/B-able knobs + scan dispatch
            # counters (flushes = boundaries, steps = body iterations)
            "decode_steps_k": eng.decode_steps,
            "decode_mode": eng.decode_mode,
            "scan_steps": eng.n_scan_steps,
            "scan_flushes": eng.n_scan_flushes,
            # sharding: model-axis shard count + per-device pool bytes
            "tp_shards": eng.tp,
            "kv_pool_bytes_per_shard": int(eng.kv.pool_bytes_per_shard),
        }

    def _stats_msg(self, engine_part: Optional[dict]) -> dict:
        # Loop-thread half (admission state, latency percentiles, pump
        # health) merged with the engine half — either the pump-built
        # consistent one, or a fresh stale read (engine_part=None).
        ms = 1e3
        lat = {name: {k: round(v * ms, 3) for k, v in
                      self.stats.percentiles(name, (50.0, 90.0, 99.0)).items()}
               for name in ("request_latency", "first_token_latency",
                            "token_latency")}
        out = {
            "type": "stats",
            "consistent": engine_part is not None,
            "inflight": self._inflight,
            "max_inflight": self.max_inflight,
            "draining": self._draining,
            "role": self.role,
            "kv_pushes": self._kv_pushes,
            "kv_push_failures": self._kv_push_failures,
            "pump_alive": self.pump_alive(),
            "pump_last_step_age_s": round(self.pump_last_step_age(), 3),
            "latency_ms": lat,
        }
        out.update(engine_part if engine_part is not None
                   else self._engine_stats())
        return out
