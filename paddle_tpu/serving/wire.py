"""Length-prefixed JSON frame protocol for the serving front end.

The TPU-native echo of the reference's length-prefixed protobuf RPC (ref:
paddle/pserver/ProtoServer.h:37 — "packet = uint32 length + body",
LightNetwork.h:41): every message on the wire is

    [4-byte big-endian unsigned length N][N bytes of UTF-8 JSON]

JSON instead of protobuf because the payloads are tiny (token ids and
knobs; the model weights never cross this wire) and the protocol must stay
debuggable with `nc` + a human eye.  Message schemas live in
docs/serving.md; the server (serving/server.py, asyncio) and the client
(serving/client.py, blocking sockets) both speak through THIS module so
the framing can never drift between them.

One payload class breaks the tiny-JSON assumption: the parameter server's
block arrays (`send_grad`/`get_params`), where base64-inside-JSON costs
~33% extra bytes plus encode/decode time on the training hot path.  For
those, a BINARY frame variant tags the length prefix's high bit (free:
MAX_FRAME is far below 2^31) and carries

    [>I : BIN_BIT | N][>I : H][H bytes UTF-8 JSON header][N-4-H raw bytes]

— the header is an ordinary message dict, the raw payload rides behind it
un-encoded and is attached to the decoded dict under `PAYLOAD_KEY`.  Both
read paths (asyncio + blocking) understand it unconditionally; SENDING it
is negotiated through hello `capabilities` ("bin_blocks") so an old peer
keeps receiving pure JSON.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional

_LEN = struct.Struct(">I")

#: refuse frames above this — a corrupt/hostile length prefix must not make
#: the receiver allocate gigabytes (64 MiB >> any real request/response)
MAX_FRAME = 64 * 1024 * 1024

#: high bit of the length prefix tags a binary frame (header + raw
#: payload); every JSON frame's length is <= MAX_FRAME << 2^31, so the bit
#: can never be set by accident on a well-formed legacy stream
BIN_BIT = 0x80000000

#: the SERVING tier's binary-frame budget: the replica server and the
#: fleet router read with `bin_cap=MAX_BIN_PAYLOAD`, so a hostile/corrupt
#: BIN length prefix on a front-end socket can never make them buffer
#: tens of megabytes (kv_push senders chunk their page payloads under
#: this).  The cap is OPT-IN per read path — the parameter-server wire
#: legitimately ships whole-shard block frames far above it and keeps the
#: plain MAX_FRAME bound.
MAX_BIN_PAYLOAD = 8 * 1024 * 1024

#: decoded binary frames carry their raw payload under this key (bytes);
#: leading underscore keeps it out of any JSON re-encode by convention
PAYLOAD_KEY = "_payload"

#: wire-protocol version, carried by the `hello` frame both the replica
#: server and the fleet router answer on connect.  Bump on any change a
#: v(n-1) peer could not parse; additive message types/fields do NOT bump
#: it (peers advertise those through `capabilities` instead).
PROTO = 1

#: one-line protocol description, used by error frames answering a
#: malformed FIRST frame — a peer that speaks the wrong protocol (an HTTP
#: client, a bare JSON line, an old binary framing) gets told what this
#: socket expects instead of a silent close.  The fleet router depends on
#: this to classify peers.
PROTO_DESC = (f"paddle_tpu serving wire protocol v{PROTO}: every message "
              f"is [4-byte big-endian length][UTF-8 JSON object]; open "
              f"with a {{\"type\": \"hello\"}} frame to negotiate")


def hello_msg(role: str, **extra) -> dict:
    """The version/capabilities frame a server answers on connect:
    `role` names what kind of peer this is ("replica" for the engine-pump
    server, "router" for the fleet front tier) so a connecting router/ctl
    can classify the far end before routing anything at it."""
    return {"type": "hello", "proto": PROTO, "role": role, **extra}


def get_trace(msg: dict) -> Optional[dict]:
    """Validated distributed-trace context off a wire frame, or None.

    Frames that cross processes (serving `generate`, pserver
    `send_grad`/`barrier`/`get_params`) may carry
    `{"trace": {"trace_id": "<hex>", "parent": "<span id>"}}` —
    docs/observability.md "Distributed tracing".  Malformed contexts are
    dropped, not fatal: tracing must never fail a request, and the
    serving replica server and the parameter server must agree on that
    rule, which is why the validation lives HERE and not in either."""
    tc = msg.get("trace")
    if isinstance(tc, dict) and isinstance(tc.get("trace_id"), str):
        out = {"trace_id": tc["trace_id"]}
        if isinstance(tc.get("parent"), str):
            out["parent"] = tc["parent"]
        return out
    return None


class FrameError(ValueError):
    """Malformed frame: oversized length prefix or non-JSON body."""


def encode(msg: dict) -> bytes:
    """One message -> length-prefixed wire bytes."""
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise FrameError(f"frame of {len(body)} bytes exceeds the "
                         f"{MAX_FRAME}-byte cap")
    return _LEN.pack(len(body)) + body


def _decode_body(body: bytes) -> dict:
    try:
        msg = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"frame body is not JSON: {e}") from e
    if not isinstance(msg, dict):
        raise FrameError(f"frame body must be a JSON object, "
                         f"got {type(msg).__name__}")
    return msg


def encode_bin(msg: dict, payload: bytes) -> bytes:
    """One message + raw payload -> binary wire frame (module docstring
    layout).  `msg` must not already carry PAYLOAD_KEY."""
    header = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    n = _LEN.size + len(header) + len(payload)
    if n > MAX_FRAME:
        raise FrameError(f"binary frame of {n} bytes exceeds the "
                         f"{MAX_FRAME}-byte cap")
    return _LEN.pack(BIN_BIT | n) + _LEN.pack(len(header)) \
        + header + payload


def _decode_bin_body(body: bytes) -> dict:
    """Binary frame body -> header dict with the raw payload attached
    under PAYLOAD_KEY."""
    if len(body) < _LEN.size:
        raise FrameError("binary frame too short for its header prefix")
    (h,) = _LEN.unpack(body[:_LEN.size])
    if h > len(body) - _LEN.size:
        raise FrameError(f"binary frame header length {h} overruns the "
                         f"{len(body)}-byte body — corrupt stream?")
    msg = _decode_body(body[_LEN.size:_LEN.size + h])
    msg[PAYLOAD_KEY] = bytes(body[_LEN.size + h:])
    return msg


def check_length(raw: bytes) -> int:
    """Validate a length prefix; returns the body length (binary-frame
    tag bit stripped — use split_length to see it)."""
    return split_length(raw)[0]


def split_length(raw: bytes,
                 bin_cap: Optional[int] = None) -> tuple[int, bool]:
    """Validate a length prefix; returns (body length, is_binary).
    `bin_cap` additionally bounds a BINARY frame's declared body — the
    serving front ends pass MAX_BIN_PAYLOAD so a corrupt/hostile prefix
    is refused BEFORE any buffering, not after 64 MiB of it."""
    (n,) = _LEN.unpack(raw)
    binary = bool(n & BIN_BIT)
    n &= ~BIN_BIT
    if n > MAX_FRAME:
        raise FrameError(f"frame length {n} exceeds the {MAX_FRAME}-byte "
                         f"cap — corrupt stream?")
    if binary and bin_cap is not None and n > bin_cap:
        raise FrameError(f"binary frame length {n} exceeds this "
                         f"endpoint's {bin_cap}-byte binary-frame cap")
    return n, binary


async def read_frame(reader, bin_cap: Optional[int] = None) \
        -> Optional[dict]:
    """One frame from an asyncio StreamReader; None on clean EOF."""
    import asyncio

    try:
        raw = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n, binary = split_length(raw, bin_cap=bin_cap)
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError) as e:
        raise FrameError(f"stream ended mid-frame ({e})") from e
    return _decode_bin_body(body) if binary else _decode_body(body)


class FrameConn:
    """One accepted client connection on an asyncio frame server — shared
    by the replica server (serving/server.py) and the fleet router
    (fleet/router.py), so the slow-reader discipline can never drift
    between the two front ends:

    a client that stops READING while its streams keep producing would
    grow the transport's send buffer without bound (token frames are
    pushed from loop callbacks, never awaiting drain) — past
    MAX_WRITE_BUFFER the connection is declared dead and closed, which
    surfaces to the owner's handler as EOF (the same path as a
    disconnect, where in-flight work gets cancelled)."""

    _seq = 0
    MAX_WRITE_BUFFER = 8 * 1024 * 1024

    def __init__(self, writer):
        FrameConn._seq += 1
        self.seq = FrameConn._seq
        self.writer = writer
        self.dead = False
        self.rids = {}             # client id -> owner's routing id

    def send(self, msg: dict) -> None:
        self._write(encode(msg))

    def send_bin(self, msg: dict, payload: bytes) -> None:
        """Binary frame variant (header + raw payload) — negotiated via
        hello capabilities; same slow-reader discipline as send()."""
        self._write(encode_bin(msg, payload))

    def _write(self, frame: bytes) -> None:
        if self.dead or self.writer.is_closing():
            return
        try:
            if self.writer.transport.get_write_buffer_size() > \
                    self.MAX_WRITE_BUFFER:
                self.dead = True   # slow reader: sever, don't buffer
                self.writer.close()
                return
            self.writer.write(frame)
        except (ConnectionError, RuntimeError):
            self.dead = True


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf  # caller distinguishes
        buf += chunk
    return buf


def read_frame_sync(sock: socket.socket,
                    bin_cap: Optional[int] = None) -> Optional[dict]:
    """One frame from a blocking socket; None on clean EOF."""
    raw = _recv_exact(sock, _LEN.size)
    if raw is None:
        return None
    if len(raw) < _LEN.size:
        raise FrameError("stream ended inside a length prefix")
    n, binary = split_length(raw, bin_cap=bin_cap)
    body = _recv_exact(sock, n)
    if body is None or len(body) < n:
        raise FrameError(f"stream ended mid-frame (wanted {n} bytes)")
    return _decode_bin_body(body) if binary else _decode_body(body)


def write_frame_sync(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode(msg))


def write_frame_bin_sync(sock: socket.socket, msg: dict,
                         payload: bytes) -> None:
    sock.sendall(encode_bin(msg, payload))
