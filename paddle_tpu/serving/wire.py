"""Length-prefixed JSON frame protocol for the serving front end.

The TPU-native echo of the reference's length-prefixed protobuf RPC (ref:
paddle/pserver/ProtoServer.h:37 — "packet = uint32 length + body",
LightNetwork.h:41): every message on the wire is

    [4-byte big-endian unsigned length N][N bytes of UTF-8 JSON]

JSON instead of protobuf because the payloads are tiny (token ids and
knobs; the model weights never cross this wire) and the protocol must stay
debuggable with `nc` + a human eye.  Message schemas live in
docs/serving.md; the server (serving/server.py, asyncio) and the client
(serving/client.py, blocking sockets) both speak through THIS module so
the framing can never drift between them.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional

_LEN = struct.Struct(">I")

#: refuse frames above this — a corrupt/hostile length prefix must not make
#: the receiver allocate gigabytes (64 MiB >> any real request/response)
MAX_FRAME = 64 * 1024 * 1024

#: wire-protocol version, carried by the `hello` frame both the replica
#: server and the fleet router answer on connect.  Bump on any change a
#: v(n-1) peer could not parse; additive message types/fields do NOT bump
#: it (peers advertise those through `capabilities` instead).
PROTO = 1

#: one-line protocol description, used by error frames answering a
#: malformed FIRST frame — a peer that speaks the wrong protocol (an HTTP
#: client, a bare JSON line, an old binary framing) gets told what this
#: socket expects instead of a silent close.  The fleet router depends on
#: this to classify peers.
PROTO_DESC = (f"paddle_tpu serving wire protocol v{PROTO}: every message "
              f"is [4-byte big-endian length][UTF-8 JSON object]; open "
              f"with a {{\"type\": \"hello\"}} frame to negotiate")


def hello_msg(role: str, **extra) -> dict:
    """The version/capabilities frame a server answers on connect:
    `role` names what kind of peer this is ("replica" for the engine-pump
    server, "router" for the fleet front tier) so a connecting router/ctl
    can classify the far end before routing anything at it."""
    return {"type": "hello", "proto": PROTO, "role": role, **extra}


def get_trace(msg: dict) -> Optional[dict]:
    """Validated distributed-trace context off a wire frame, or None.

    Frames that cross processes (serving `generate`, pserver
    `send_grad`/`barrier`/`get_params`) may carry
    `{"trace": {"trace_id": "<hex>", "parent": "<span id>"}}` —
    docs/observability.md "Distributed tracing".  Malformed contexts are
    dropped, not fatal: tracing must never fail a request, and the
    serving replica server and the parameter server must agree on that
    rule, which is why the validation lives HERE and not in either."""
    tc = msg.get("trace")
    if isinstance(tc, dict) and isinstance(tc.get("trace_id"), str):
        out = {"trace_id": tc["trace_id"]}
        if isinstance(tc.get("parent"), str):
            out["parent"] = tc["parent"]
        return out
    return None


class FrameError(ValueError):
    """Malformed frame: oversized length prefix or non-JSON body."""


def encode(msg: dict) -> bytes:
    """One message -> length-prefixed wire bytes."""
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise FrameError(f"frame of {len(body)} bytes exceeds the "
                         f"{MAX_FRAME}-byte cap")
    return _LEN.pack(len(body)) + body


def _decode_body(body: bytes) -> dict:
    try:
        msg = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"frame body is not JSON: {e}") from e
    if not isinstance(msg, dict):
        raise FrameError(f"frame body must be a JSON object, "
                         f"got {type(msg).__name__}")
    return msg


def check_length(raw: bytes) -> int:
    """Validate a length prefix; returns the body length."""
    (n,) = _LEN.unpack(raw)
    if n > MAX_FRAME:
        raise FrameError(f"frame length {n} exceeds the {MAX_FRAME}-byte "
                         f"cap — corrupt stream?")
    return n


async def read_frame(reader) -> Optional[dict]:
    """One frame from an asyncio StreamReader; None on clean EOF."""
    import asyncio

    try:
        raw = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n = check_length(raw)
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError) as e:
        raise FrameError(f"stream ended mid-frame ({e})") from e
    return _decode_body(body)


class FrameConn:
    """One accepted client connection on an asyncio frame server — shared
    by the replica server (serving/server.py) and the fleet router
    (fleet/router.py), so the slow-reader discipline can never drift
    between the two front ends:

    a client that stops READING while its streams keep producing would
    grow the transport's send buffer without bound (token frames are
    pushed from loop callbacks, never awaiting drain) — past
    MAX_WRITE_BUFFER the connection is declared dead and closed, which
    surfaces to the owner's handler as EOF (the same path as a
    disconnect, where in-flight work gets cancelled)."""

    _seq = 0
    MAX_WRITE_BUFFER = 8 * 1024 * 1024

    def __init__(self, writer):
        FrameConn._seq += 1
        self.seq = FrameConn._seq
        self.writer = writer
        self.dead = False
        self.rids = {}             # client id -> owner's routing id

    def send(self, msg: dict) -> None:
        if self.dead or self.writer.is_closing():
            return
        try:
            if self.writer.transport.get_write_buffer_size() > \
                    self.MAX_WRITE_BUFFER:
                self.dead = True   # slow reader: sever, don't buffer
                self.writer.close()
                return
            self.writer.write(encode(msg))
        except (ConnectionError, RuntimeError):
            self.dead = True


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf  # caller distinguishes
        buf += chunk
    return buf


def read_frame_sync(sock: socket.socket) -> Optional[dict]:
    """One frame from a blocking socket; None on clean EOF."""
    raw = _recv_exact(sock, _LEN.size)
    if raw is None:
        return None
    if len(raw) < _LEN.size:
        raise FrameError("stream ended inside a length prefix")
    n = check_length(raw)
    body = _recv_exact(sock, n)
    if body is None or len(body) < n:
        raise FrameError(f"stream ended mid-frame (wanted {n} bytes)")
    return _decode_body(body)


def write_frame_sync(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode(msg))
