"""Length-prefixed JSON frame protocol for the serving front end.

The TPU-native echo of the reference's length-prefixed protobuf RPC (ref:
paddle/pserver/ProtoServer.h:37 — "packet = uint32 length + body",
LightNetwork.h:41): every message on the wire is

    [4-byte big-endian unsigned length N][N bytes of UTF-8 JSON]

JSON instead of protobuf because the payloads are tiny (token ids and
knobs; the model weights never cross this wire) and the protocol must stay
debuggable with `nc` + a human eye.  Message schemas live in
docs/serving.md; the server (serving/server.py, asyncio) and the client
(serving/client.py, blocking sockets) both speak through THIS module so
the framing can never drift between them.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional

_LEN = struct.Struct(">I")

#: refuse frames above this — a corrupt/hostile length prefix must not make
#: the receiver allocate gigabytes (64 MiB >> any real request/response)
MAX_FRAME = 64 * 1024 * 1024


class FrameError(ValueError):
    """Malformed frame: oversized length prefix or non-JSON body."""


def encode(msg: dict) -> bytes:
    """One message -> length-prefixed wire bytes."""
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise FrameError(f"frame of {len(body)} bytes exceeds the "
                         f"{MAX_FRAME}-byte cap")
    return _LEN.pack(len(body)) + body


def _decode_body(body: bytes) -> dict:
    try:
        msg = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"frame body is not JSON: {e}") from e
    if not isinstance(msg, dict):
        raise FrameError(f"frame body must be a JSON object, "
                         f"got {type(msg).__name__}")
    return msg


def check_length(raw: bytes) -> int:
    """Validate a length prefix; returns the body length."""
    (n,) = _LEN.unpack(raw)
    if n > MAX_FRAME:
        raise FrameError(f"frame length {n} exceeds the {MAX_FRAME}-byte "
                         f"cap — corrupt stream?")
    return n


async def read_frame(reader) -> Optional[dict]:
    """One frame from an asyncio StreamReader; None on clean EOF."""
    import asyncio

    try:
        raw = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n = check_length(raw)
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError) as e:
        raise FrameError(f"stream ended mid-frame ({e})") from e
    return _decode_body(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf  # caller distinguishes
        buf += chunk
    return buf


def read_frame_sync(sock: socket.socket) -> Optional[dict]:
    """One frame from a blocking socket; None on clean EOF."""
    raw = _recv_exact(sock, _LEN.size)
    if raw is None:
        return None
    if len(raw) < _LEN.size:
        raise FrameError("stream ended inside a length prefix")
    n = check_length(raw)
    body = _recv_exact(sock, n)
    if body is None or len(body) < n:
        raise FrameError(f"stream ended mid-frame (wanted {n} bytes)")
    return _decode_body(body)


def write_frame_sync(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode(msg))
