"""Host-side draft-token proposers for speculative decoding.

The serving engine's speculative path (docs/serving.md "Speculative
decoding") multiplies decode tokens/s by letting a cheap DRAFTER guess
the next k tokens of a slot and having the target model score all k+1
positions in ONE ragged dispatch — exactly the mixed-step machinery of
PR 8, pointed at the future instead of the prompt.  Verification is
exact (the engine samples every chain position with the slot's own key
schedule and accepts only the matching prefix), so a drafter can NEVER
change a single emitted token — only how many compiled steps it takes
to emit them.  A useless drafter costs some wasted verify rows; a good
one collapses k+1 sequential steps into one.

The drafter interface is deliberately tiny so a small draft MODEL can
slot in later:

    class Drafter:
        def propose(self, ctx: np.ndarray, k: int) -> np.ndarray:
            '''Up to `k` int32 draft tokens continuing `ctx` (the slot's
            prompt + everything generated so far, newest last).  May
            return fewer (or zero) tokens; must be DETERMINISTIC in ctx
            — the engine consults it on the scheduling hot path, between
            compiled steps, on the pump thread.'''

The default is prompt-lookup / n-gram drafting (the "no second model"
scheme of arXiv-era LLMA/prompt-lookup decoding): the continuation of
the most recent earlier occurrence of the slot's own trailing n-gram.
Free to compute, surprisingly strong on the workloads serving actually
sees (retrieval contexts, code, templated text, and the repetitive
regimes of constrained decoding), and exactly zero-cost to correctness
by construction.
"""

from __future__ import annotations

import numpy as np


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most
    recent earlier occurrence of the context's trailing n-gram.

    Tries match lengths `max_ngram` down to `min_ngram`; the FIRST
    length with a hit wins, and among hits the MOST RECENT occurrence
    is used (recency tracks local repetition best).  Pure numpy over
    the slot's own tokens — no model, no device work, deterministic.

    `window` bounds the searched context to its most recent tokens: the
    lookup runs on the scheduling hot path (pump thread, between
    compiled steps, once per decoding slot), so its cost must stay O(1)
    in generation length — and recency is the signal anyway.  The
    engine reads this attribute to hand over only the tail.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 256):
        assert 1 <= min_ngram <= max_ngram
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.window = int(window)

    def propose(self, ctx: np.ndarray, k: int) -> np.ndarray:
        ctx = np.asarray(ctx, np.int32).reshape(-1)[-self.window:]
        n_ctx = ctx.size
        if k <= 0 or n_ctx < 2:
            return np.zeros(0, np.int32)
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            pat = ctx[n_ctx - n:]
            # windows over ctx[:-1]: every start whose continuation has
            # at least one token to propose
            wins = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.flatnonzero((wins == pat[None, :]).all(axis=1))
            if hits.size:
                start = int(hits[-1]) + n          # most recent match
                return ctx[start:start + k].copy()
        return np.zeros(0, np.int32)
