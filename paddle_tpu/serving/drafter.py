"""Draft-token proposers for speculative decoding.

The serving engine's speculative path (docs/serving.md "Speculative
decoding") multiplies decode tokens/s by letting a cheap DRAFTER guess
the next k tokens of a slot and having the target model score all k+1
positions in ONE ragged dispatch — exactly the mixed-step machinery of
PR 8, pointed at the future instead of the prompt.  Verification is
exact (the engine samples every chain position with the slot's own key
schedule and accepts only the matching prefix), so a drafter can NEVER
change a single emitted token — only how many compiled steps it takes
to emit them.  A useless drafter costs some wasted verify rows; a good
one collapses k+1 sequential steps into one.

Two interfaces, both honored by the engine:

    class Drafter:
        def propose(self, ctx: np.ndarray, k: int,
                    eos_id: int = -1) -> np.ndarray:
            '''Up to `k` int32 draft tokens continuing `ctx` (the slot's
            prompt + everything generated so far, newest last).  May
            return fewer (or zero) tokens; must be DETERMINISTIC in ctx
            — the engine consults it on the scheduling hot path, between
            compiled steps, on the pump thread.  The CLAMP CONTRACT is
            the drafter's, not the engine's: never more than k tokens,
            and never a token past the first `eos_id` — the engine
            asserts instead of silently truncating, so a drafter bug
            shows up as a tripwire, not as skewed accept-rate stats.'''

        def propose_batch(self, ctx: np.ndarray, lens: np.ndarray,
                          k: int, eos_ids: np.ndarray) -> np.ndarray:
            '''OPTIONAL batched form: [S, W] windowed contexts (row s
            valid through lens[s], zero-padded right) -> [S, k] int32
            proposals in ONE call, row s clamped at eos_ids[s] with -1
            padding after the clamp.  When present the engine prefers
            it: one device dispatch drafts for every decoding slot.'''

`NgramDrafter` (the default) is host-side prompt lookup; `ModelDrafter`
runs a real draft transformer — a separately-trained tiny model, an
embedding-distilled one, or the TARGET over a truncated window
(self-speculation) — batched across all slots in one jitted dispatch
(compile-watch site `serving.draft_step`, ONE signature per (S, k)).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def clamp_proposal(d: np.ndarray, k: int, eos_id: int = -1) -> np.ndarray:
    """The drafter-side clamp every propose() must apply: at most `k`
    tokens, truncated just AFTER the first `eos_id` (a drafted eos can
    be accepted and retire the slot; tokens past it could never be
    banked, and scoring them would skew the accept rate the dynamic-k
    policy steers by)."""
    d = np.asarray(d, np.int32).reshape(-1)[:max(0, int(k))]
    if eos_id >= 0 and d.size:
        hit = np.flatnonzero(d == eos_id)
        if hit.size:
            d = d[:int(hit[0]) + 1]
    return d


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most
    recent earlier occurrence of the context's trailing n-gram.

    Tries match lengths `max_ngram` down to `min_ngram`; the FIRST
    length with a hit wins, and among hits the MOST RECENT occurrence
    is used (recency tracks local repetition best).  Pure numpy over
    the slot's own tokens — no model, no device work, deterministic.

    `window` bounds the searched context to its most recent tokens: the
    lookup runs on the scheduling hot path (pump thread, between
    compiled steps, once per decoding slot), so its cost must stay O(1)
    in generation length — and recency is the signal anyway.  The
    engine reads this attribute to hand over only the tail.
    """

    kind = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 256):
        assert 1 <= min_ngram <= max_ngram
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.window = int(window)

    def propose(self, ctx: np.ndarray, k: int,
                eos_id: int = -1) -> np.ndarray:
        ctx = np.asarray(ctx, np.int32).reshape(-1)[-self.window:]
        n_ctx = ctx.size
        if k <= 0 or n_ctx < 2:
            return np.zeros(0, np.int32)
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            pat = ctx[n_ctx - n:]
            # windows over ctx[:-1]: every start whose continuation has
            # at least one token to propose
            wins = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.flatnonzero((wins == pat[None, :]).all(axis=1))
            if hits.size:
                start = int(hits[-1]) + n          # most recent match
                return clamp_proposal(ctx[start:start + k], k, eos_id)
        return np.zeros(0, np.int32)


class ModelDrafter:
    """Draft-model proposer: greedy k-chains for ALL decoding slots in
    ONE jitted batched dispatch.

    The engine hands over [S, W] windowed contexts (`window` caps W; the
    engine reads the attribute, exactly as for NgramDrafter) plus valid
    lengths, and gets back [S, k] greedy proposals from ONE compiled
    program — compile-watch site `serving.draft_step`, ONE signature per
    (S, k): S is the engine's fixed slot count and k is static, so a
    steady spec deployment never grows the jit cache.  The rollout is
    `graph/lm_decode.py:build_draft_roll` — k whole-window forwards of
    whatever LM `executor`/`params` hold, under `lax.scan`.

    Three ways to get one:
      * `ModelDrafter(executor, params)` — a separately-trained tiny
        draft LM (any config whose logits layer is [B, T, vocab]).
      * `ModelDrafter.from_target(executor, params)` — SELF-SPECULATION:
        the target model drafts for itself over a truncated window.
        Zero extra weights; the window cap is the speedup (k drafts cost
        k short-window forwards instead of k full paged-decode
        dispatches), and greedy agreement with the target is high by
        construction — the strongest drafter this repo can build without
        a training run.
      * `ModelDrafter.distilled_init(executor, params, dim=..)` — a
        fresh tiny transformer whose token embedding (and tied LM head)
        are sliced out of the TARGET's embedding: cheap geometric
        alignment so an untrained drafter starts correlated with the
        target's token space instead of fully random.

    Replication contract for tensor-parallel serving: the drafter holds
    its params as given (host/replicated), never the engine's sharded
    copies — its program compiles with ZERO collectives under any mesh
    (tools/hlo_shard_check.py lowers and proves it), so drafting can
    never add cross-device traffic to the verify step it feeds.
    """

    kind = "model"

    def __init__(self, executor, params, window: int = 64,
                 input_name: Optional[str] = None,
                 logits_name: Optional[str] = None):
        import copy

        import jax

        from paddle_tpu.graph.lm_decode import build_draft_roll
        from paddle_tpu.obs.compile_watch import get_compile_watch

        # the replication contract, enforced: a tensor-parallel engine
        # stamps its mesh onto the (shared) executor, whose forward then
        # emits per-layer sharding constraints — tracing the draft
        # rollout through it would compile Megatron all-reduces into the
        # draft step.  An UNCONDITIONAL mesh-free shallow copy keeps the
        # drafter's program single-device/replicated regardless of what
        # the engine sharded — and regardless of whether the drafter was
        # built before or after the engine stamped the mesh (the rollout
        # reads executor.mesh at TRACE time, not here)
        # (tools/hlo_shard_check.py lowers it and proves zero
        # collectives).
        if hasattr(executor, "mesh"):
            executor = copy.copy(executor)
            executor.mesh = None
        self.executor = executor
        self.params = params
        self.window = int(window)
        assert self.window >= 2, "draft window must hold an n-gram"
        self._step = get_compile_watch().wrap_jit(
            "serving.draft_step",
            jax.jit(build_draft_roll(executor, input_name=input_name,
                                     logits_name=logits_name),
                    static_argnums=(3,)))

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_target(cls, executor, params, window: int = 64):
        """Self-speculation: the target drafts for itself over a
        truncated window.  Pass the HOST params (what you gave the
        engine), not the engine's possibly-sharded copies."""
        return cls(executor, params, window=window)

    @classmethod
    def distilled_init(cls, executor, params, dim: int = 32,
                       layers: int = 1, heads: int = 2,
                       window: int = 64, seed: int = 0,
                       embedding_name: str = "_tok_embedding",
                       head_name: Optional[str] = None):
        """Build a tiny transformer drafter whose embedding (and tied LM
        head) are distilled-initialized from the TARGET's token
        embedding: the first `dim` embedding columns are copied in, and
        the draft LM head is the copied embedding's transpose (weight
        tying), so the untrained drafter's greedy picks already follow
        the target's token geometry.  `executor`/`params` are the
        TARGET's; vocab is read off its embedding table."""
        import numpy as np

        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.trainer.trainer import Trainer

        emb = np.asarray(params[embedding_name], np.float32)
        vocab, tdim = emb.shape
        dim = min(int(dim), tdim)
        cfg = parse_config(
            "demo/model_zoo/transformer_lm.py",
            f"vocab={vocab},dim={dim},layers={int(layers)},"
            f"heads={int(heads)},batch_size=1")
        tr = Trainer(cfg, seed=seed)
        draft = dict(tr.params)
        draft[embedding_name] = emb[:, :dim].copy()
        if head_name is None:
            head_name = next((n for n in draft
                              if n.startswith("_lm_head")), None)
        if head_name is not None and \
                np.asarray(draft[head_name]).shape == (dim, vocab):
            draft[head_name] = np.ascontiguousarray(emb[:, :dim].T)
        return cls(tr.executor, draft, window=window)

    # -- proposing ---------------------------------------------------------
    def propose_batch(self, ctx: np.ndarray, lens: np.ndarray, k: int,
                      eos_ids: Optional[np.ndarray] = None) -> np.ndarray:
        """[S, W] windowed contexts + [S] valid lengths -> [S, k] greedy
        proposals in ONE jitted dispatch.  Row s is clamped just after
        its first eos_ids[s] and padded with -1 (the engine treats -1 as
        end-of-proposal; -1 is never a vocab id)."""
        import jax.numpy as jnp

        S = int(ctx.shape[0])
        k = int(k)
        if k <= 0:
            return np.zeros((S, 0), np.int32)
        W = int(ctx.shape[1])
        buf = np.zeros((S, W + k), np.int32)
        buf[:, :W] = ctx
        lens = np.clip(np.asarray(lens, np.int32), 1, W)
        out = np.asarray(self._step(self.params, jnp.asarray(buf),
                                    jnp.asarray(lens), k))
        if eos_ids is not None:
            eos = np.asarray(eos_ids, np.int32)[:, None]     # [S, 1]
            past = np.zeros((S, k), bool)
            hit = (out == eos) & (eos >= 0)
            if k > 1:
                past[:, 1:] = np.cumsum(hit[:, :-1], axis=1) > 0
            out = np.where(past, -1, out)
        return out.astype(np.int32)

    def propose(self, ctx: np.ndarray, k: int,
                eos_id: int = -1) -> np.ndarray:
        """Single-context fallback (the generic engine path and the unit
        tests): one row through the same batched program.  Note each
        distinct (1, k) shape is its own draft-step signature — the
        engine's batched path is the production one."""
        ctx = np.asarray(ctx, np.int32).reshape(-1)[-self.window:]
        if k <= 0 or ctx.size == 0:
            return np.zeros(0, np.int32)
        row = np.zeros((1, self.window), np.int32)
        row[0, :ctx.size] = ctx
        out = self.propose_batch(row, np.array([ctx.size]), int(k))
        return clamp_proposal(out[0], k, eos_id)
