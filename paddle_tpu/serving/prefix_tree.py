"""Radix prefix index over committed KV pages — host-side prefix caching.

Production serving traffic is dominated by shared prompt prefixes (system
prompts, few-shot templates, multi-turn chat history), yet every admission
used to pay full prefill even when the first N pages of KV were
bit-identical to work already done.  The page-table indirection of
serving/paged_kv.py makes sharing nearly free on the device side: a cached
prefix is just table entries pointing at already-committed physical pages
(the prefix-cache configuration of the Ragged Paged Attention line,
arXiv:2604.15464, on the slot/page serving design of arXiv:2605.25645).

The index is a radix tree keyed on token-id runs at PAGE granularity: each
node covers exactly `page_size` consecutive token ids and names the one
physical page holding their committed K/V.  A path from the root spells a
prompt prefix in whole pages.  On top of the full-page walk, `match` also
probes ONE page deeper for a partial-run match — a child whose run starts
with the remaining (< page_size) tokens.  Mapping that boundary page gives
the admission up to page_size-1 more cached tokens; because the request
will write its own divergent suffix into that page mid-run, the engine
must copy-on-write it first (PagedKVCache.ensure_writable) — the "COW
divergence mid-page" case.

Ownership: the tree holds pages via PagedKVCache's `_cached` mark (no
refcount of its own).  A node whose page no slot maps (`_ref == 0`) is
reclaimable; when the allocator runs out of pages it calls `evict_for`
(wired as `kv.on_page_pressure`), which evicts least-recently-used LEAVES
first — leaf-first keeps the prefix property (a parent outlives its
children), and refcount-zero-first means eviction never steals a page out
from under a live slot.  Eviction runs BEFORE the engine pauses slots;
preemption stays last resort.

Single-threaded by design: all calls happen on the engine's step()-driving
thread (the pump), like the rest of the scheduler state.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from paddle_tpu.obs.flight import get_flight_recorder


class _Node:
    __slots__ = ("run", "page", "parent", "children", "by_first",
                 "last_use")

    def __init__(self, run: tuple, page: int, parent: Optional["_Node"]):
        self.run = run                  # page_size token ids (() for root)
        self.page = page                # physical page id (-1 for root)
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        # first-token index over children: the partial-boundary probe
        # scans only runs sharing the probe's first token — donation adds
        # one divergent-boundary child per retired suffix under a hot
        # prefix node, and a linear scan there would put O(children)
        # admission cost on the hottest prefix exactly
        self.by_first: dict[int, dict[tuple, _Node]] = {}
        self.last_use = 0

    def add_child(self, child: "_Node") -> None:
        self.children[child.run] = child
        self.by_first.setdefault(child.run[0], {})[child.run] = child

    def drop_child(self, child: "_Node") -> None:
        del self.children[child.run]
        d = self.by_first[child.run[0]]
        del d[child.run]
        if not d:
            del self.by_first[child.run[0]]


class PrefixTree:
    """Radix index over committed pages of one PagedKVCache."""

    def __init__(self, kv):
        self.kv = kv
        self.ps = int(kv.page_size)
        self.root = _Node((), -1, None)
        self._clock = 0
        self.flight = get_flight_recorder()
        self.n_nodes = 0
        self.n_evictions = 0

    # -- LRU ---------------------------------------------------------------
    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_use = self._clock

    # -- lookup ------------------------------------------------------------
    def match(self, tokens) -> tuple[list[int], Optional[tuple[int, int]]]:
        """Longest cached prefix of `tokens`: returns
        (full_page_ids, partial) where `full_page_ids` are the physical
        pages of the matched whole-page runs, and `partial` is
        (boundary_page_id, r) when a child's run additionally matches the
        next r (1 <= r < page_size... or up to the tokens left) tokens —
        the caller maps that page too and MUST copy-on-write it before its
        first write.  Ties between partially-matching children break
        deterministically (longest match, then smallest run).  Touches the
        matched path for LRU."""
        toks = np.asarray(tokens).reshape(-1)
        node, pages = self.root, []
        i, n = 0, int(toks.size)
        while n - i >= self.ps:
            run = tuple(int(t) for t in toks[i:i + self.ps])
            child = node.children.get(run)
            if child is None:
                break
            node = child
            self._touch(node)
            pages.append(child.page)
            i += self.ps
        partial = None
        rest = tuple(int(t) for t in toks[i:i + self.ps])
        if rest:
            best, best_r = None, 0
            # only children whose run starts with the probe's first token
            # can match (r >= 1) — the by_first index skips the rest
            for run, child in node.by_first.get(rest[0], {}).items():
                r = 1
                while r < len(rest) and run[r] == rest[r]:
                    r += 1
                if r > best_r or (r == best_r and
                                  best is not None and run < best.run):
                    best, best_r = child, r
            if best is not None:
                self._touch(best)
                partial = (best.page, best_r)
        return pages, partial

    # -- insertion (donation at retire/preempt/abort) ----------------------
    def insert(self, tokens, pages) -> int:
        """Register `len(pages)` fully-committed pages: pages[j] holds the
        K/V of tokens[j*ps:(j+1)*ps].  A run already present keeps its
        existing physical page (the donated duplicate stays with the
        donor's normal release flow — it frees when the slot lets go);
        new runs retain their page via kv.cache_page.  Returns the number
        of nodes added."""
        toks = np.asarray(tokens).reshape(-1)
        assert toks.size >= len(pages) * self.ps
        node, added = self.root, 0
        for j, page in enumerate(pages):
            run = tuple(int(t) for t in toks[j * self.ps:(j + 1) * self.ps])
            child = node.children.get(run)
            if child is None:
                child = _Node(run, int(page), node)
                node.add_child(child)
                self.kv.cache_page(int(page))
                self.n_nodes += 1
                added += 1
            self._touch(child)
            node = child
        return added

    # -- eviction (the allocator's page-pressure hook) ----------------------
    def _evictable_leaves(self):
        out = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.kv._ref[node.page] == 0:
                out.append(node)
        return out

    def evict_for(self, n_pages: int) -> int:
        """Reclaim up to `n_pages` pages by evicting LRU leaves whose page
        no slot maps.  Returns pages actually freed.  Wired as
        `kv.on_page_pressure`, so try_grow/COW call here before failing —
        eviction before pausing slots, preemption last resort.

        One tree walk per CALL, not per freed page: the evictable leaves
        go into a min-heap on last_use, and a victim's parent enters the
        heap the moment it becomes a childless refcount-zero node — the
        multi-page reclaim an overcommitted admission needs is
        O(nodes + freed·log nodes), not O(freed·nodes), precisely when
        the pool is under the pressure eviction exists to relieve.
        Single-threaded with the allocator, so no heap entry goes stale
        mid-call; ties on last_use (never-touched nodes share 0) break by
        insertion order."""
        import heapq

        freed = 0
        heap = []
        for i, nd in enumerate(self._evictable_leaves()):
            heap.append((nd.last_use, i, nd))
        heapq.heapify(heap)
        seq = len(heap)
        while freed < int(n_pages) and heap:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            parent.drop_child(victim)
            self.kv.uncache_page(victim.page)
            self.n_nodes -= 1
            self.n_evictions += 1
            freed += 1
            self.flight.record("prefix_evict", page=int(victim.page),
                               nodes_left=self.n_nodes)
            if parent is not self.root and not parent.children and \
                    self.kv._ref[parent.page] == 0:
                heapq.heappush(heap, (parent.last_use, seq, parent))
                seq += 1
        return freed

    def clear(self) -> None:
        """Forget everything WITHOUT touching allocator state — pair with
        kv.reset(), which already drops the `_cached` marks."""
        self.root = _Node((), -1, None)
        self.n_nodes = 0
