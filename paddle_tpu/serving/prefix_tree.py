"""Radix prefix index over committed KV pages — host-side prefix caching.

Production serving traffic is dominated by shared prompt prefixes (system
prompts, few-shot templates, multi-turn chat history), yet every admission
used to pay full prefill even when the first N pages of KV were
bit-identical to work already done.  The page-table indirection of
serving/paged_kv.py makes sharing nearly free on the device side: a cached
prefix is just table entries pointing at already-committed physical pages
(the prefix-cache configuration of the Ragged Paged Attention line,
arXiv:2604.15464, on the slot/page serving design of arXiv:2605.25645).

The index is a radix tree keyed on token-id runs at PAGE granularity: each
node covers exactly `page_size` consecutive token ids and names the one
physical page holding their committed K/V.  A path from the root spells a
prompt prefix in whole pages.  On top of the full-page walk, `match` also
probes ONE page deeper for a partial-run match — a child whose run starts
with the remaining (< page_size) tokens.  Mapping that boundary page gives
the admission up to page_size-1 more cached tokens; because the request
will write its own divergent suffix into that page mid-run, the engine
must copy-on-write it first (PagedKVCache.ensure_writable) — the "COW
divergence mid-page" case.

Ownership: the tree holds pages via PagedKVCache's `_cached` mark (no
refcount of its own).  A node whose page no slot maps (`_ref == 0`) is
reclaimable; when the allocator runs out of pages it calls `evict_for`
(wired as `kv.on_page_pressure`), which evicts least-recently-used LEAVES
first — leaf-first keeps the prefix property (a parent outlives its
children), and refcount-zero-first means eviction never steals a page out
from under a live slot.  Eviction runs BEFORE the engine pauses slots;
preemption stays last resort.

TWO-LEVEL EVICTION (the KV spill tier, docs/serving.md): with a non-zero
`kv.spill_bytes_budget`, a device-eviction victim is first offered to the
host tier — the node keeps its tokens but trades `page` for `host_id`
(spilled, resident HOST) instead of being destroyed.  Residency obeys ONE
invariant: a HOST node's entire subtree is HOST (spill order is
device-frontier-first), so "no DEVICE child" is exactly "no DEVICE
descendant" and the device-eviction frontier stays cheap to find.  Budget
room inside the host tier comes from dropping the least-recently-used
HOST leaves (LRU *within* the tier); destroying a device node whose
children already spilled drops that HOST subtree with it, keeping the
invariant.  A prefix hit on a spilled run restores through the engine's
admission path (`match_nodes` + `promote`), never here.  Node residency:
DEVICE = `page > 0, host_id None`; HOST = `page == -1, host_id int`;
detached/destroyed nodes zero both, so a stale reference can be told
from a live one.

Single-threaded by design: all calls happen on the engine's step()-driving
thread (the pump), like the rest of the scheduler state.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from paddle_tpu.obs.flight import get_flight_recorder


class _Node:
    __slots__ = ("run", "page", "parent", "children", "by_first",
                 "last_use", "host_id")

    def __init__(self, run: tuple, page: int, parent: Optional["_Node"]):
        self.run = run                  # page_size token ids (() for root)
        self.page = page                # physical page id (-1 for root
        self.host_id = None             # and HOST/spilled nodes, which
        self.parent = parent            # carry a host-tier id instead)
        self.children: dict[tuple, _Node] = {}
        # first-token index over children: the partial-boundary probe
        # scans only runs sharing the probe's first token — donation adds
        # one divergent-boundary child per retired suffix under a hot
        # prefix node, and a linear scan there would put O(children)
        # admission cost on the hottest prefix exactly
        self.by_first: dict[int, dict[tuple, _Node]] = {}
        self.last_use = 0

    def add_child(self, child: "_Node") -> None:
        self.children[child.run] = child
        self.by_first.setdefault(child.run[0], {})[child.run] = child

    def drop_child(self, child: "_Node") -> None:
        del self.children[child.run]
        d = self.by_first[child.run[0]]
        del d[child.run]
        if not d:
            del self.by_first[child.run[0]]


class PrefixTree:
    """Radix index over committed pages of one PagedKVCache."""

    def __init__(self, kv):
        self.kv = kv
        self.ps = int(kv.page_size)
        self.root = _Node((), -1, None)
        self._clock = 0
        self.flight = get_flight_recorder()
        self.n_nodes = 0
        self.n_evictions = 0
        # the engine's restore path sets this while it allocates fresh
        # device pages: pressure eviction then destroys instead of
        # spilling, so the host tier (and the hids mid-restore) stays
        # stable under the restore's own allocation
        self._spill_inhibit = False

    # -- LRU ---------------------------------------------------------------
    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_use = self._clock

    # -- lookup ------------------------------------------------------------
    def match_nodes(self, tokens) -> \
            tuple[list["_Node"], Optional[tuple["_Node", int]]]:
        """Longest cached prefix of `tokens` as NODES, residency-blind:
        the full-page path may end in HOST nodes (the residency invariant
        guarantees device-prefix-then-host-suffix order along any path),
        and `partial` is (boundary_node, r) when a child's run
        additionally matches the next r (1 <= r < page_size, or up to the
        tokens left) tokens.  The engine's admission restores any HOST
        tail before mapping; `match` below is the device-only view.  Ties
        between partially-matching children break deterministically
        (longest match, then smallest run).  Touches the matched path for
        LRU."""
        toks = np.asarray(tokens).reshape(-1)
        node, nodes = self.root, []
        i, n = 0, int(toks.size)
        while n - i >= self.ps:
            run = tuple(int(t) for t in toks[i:i + self.ps])
            child = node.children.get(run)
            if child is None:
                break
            node = child
            self._touch(node)
            nodes.append(child)
            i += self.ps
        partial = None
        rest = tuple(int(t) for t in toks[i:i + self.ps])
        if rest:
            best, best_r = None, 0
            # only children whose run starts with the probe's first token
            # can match (r >= 1) — the by_first index skips the rest
            for run, child in node.by_first.get(rest[0], {}).items():
                r = 1
                while r < len(rest) and run[r] == rest[r]:
                    r += 1
                if r > best_r or (r == best_r and
                                  best is not None and run < best.run):
                    best, best_r = child, r
            if best is not None:
                self._touch(best)
                partial = (best, best_r)
        return nodes, partial

    def match(self, tokens) -> tuple[list[int], Optional[tuple[int, int]]]:
        """The DEVICE-resident view of match_nodes: physical page ids of
        the matched whole-page runs up to the first spilled node, plus
        (boundary_page_id, r) when the partial boundary is device-resident
        and every full run before it was.  The caller maps the partial
        page too and MUST copy-on-write it before its first write.
        Spill-unaware callers (and a budget-zero engine) see exactly the
        pre-spill behavior."""
        nodes, partial = self.match_nodes(tokens)
        pages = []
        for nd in nodes:
            if nd.host_id is not None:
                return pages, None
            pages.append(nd.page)
        if partial is not None and partial[0].host_id is None:
            return pages, (partial[0].page, partial[1])
        return pages, None

    # -- insertion (donation at retire/preempt/abort) ----------------------
    def insert(self, tokens, pages, adopted: bool = False) -> int:
        """Register `len(pages)` fully-committed pages: pages[j] holds the
        K/V of tokens[j*ps:(j+1)*ps].  A run already present keeps its
        existing physical page (the donated duplicate stays with the
        donor's normal release flow — it frees when the slot lets go);
        new runs retain their page via kv.cache_page.  Returns the number
        of nodes added.

        `adopted=True` is the cross-replica MOUNT path (a kv_push import,
        docs/serving.md "Disaggregated prefill/decode"): the pages came
        through kv.adopt_restored — already prefix-retained, mapped by no
        slot — so new and promoted runs skip cache_page (which demands a
        donor mapping), and a run already DEVICE-resident frees the
        redundant imported page right here via uncache_page (there is no
        donor slot whose release would reclaim it)."""
        toks = np.asarray(tokens).reshape(-1)
        assert toks.size >= len(pages) * self.ps
        node, added = self.root, 0
        for j, page in enumerate(pages):
            run = tuple(int(t) for t in toks[j * self.ps:(j + 1) * self.ps])
            child = node.children.get(run)
            if child is None:
                child = _Node(run, int(page), node)
                node.add_child(child)
                if not adopted:
                    self.kv.cache_page(int(page))
                self.n_nodes += 1
                added += 1
            elif child.host_id is not None:
                # re-donation of a spilled run: the donor just committed
                # a bit-identical device page (same token path, same
                # deterministic prefill), so adopt it and drop the host
                # copy — cheaper than ever restoring this one.  Insert
                # walks top-down, so a promoted node's ancestors promoted
                # in this same call: the residency invariant holds.
                self.kv.drop_host_page(child.host_id, reason="drain")
                child.host_id = None
                child.page = int(page)
                if not adopted:
                    self.kv.cache_page(int(page))
            elif adopted:
                # the run is already DEVICE-resident: the imported copy is
                # bit-identical (same token path, deterministic prefill),
                # keep the incumbent and free the duplicate now
                self.kv.uncache_page(int(page))
            self._touch(child)
            node = child
        return added

    # -- eviction (the allocator's page-pressure hook) ----------------------
    def _evictable_leaves(self):
        """The device-eviction frontier: DEVICE nodes whose page no slot
        maps and with no DEVICE children.  By the residency invariant a
        HOST child has a HOST subtree, so "no DEVICE child" is "no DEVICE
        descendant" — spilling (or destroying, host subtree included) a
        frontier node keeps parents outliving device children."""
        out = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.host_id is not None:
                continue                 # HOST subtree: nothing device below
            dev = [c for c in node.children.values() if c.host_id is None]
            if dev:
                stack.extend(dev)
            elif self.kv._ref[node.page] == 0:
                out.append(node)
        return out

    def _host_leaves(self):
        """Tree leaves resident HOST — the host tier's LRU victim set.
        Non-empty whenever the tier is (every host entry is named by a
        node, and a deepest HOST node is a leaf)."""
        out = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node.host_id is not None:
                out.append(node)
        return out

    def _drop_host_node(self, node: "_Node", reason: str = "evict") -> None:
        """Detach one HOST leaf and forget its host entry."""
        node.parent.drop_child(node)
        self.kv.drop_host_page(node.host_id, reason=reason)
        node.host_id = None
        self.n_nodes -= 1
        if reason == "evict":
            self.flight.record("prefix_evict", host=True,
                               nodes_left=self.n_nodes)

    def drop_host_subtree(self, top: "_Node") -> None:
        """Detach `top` and its all-HOST subtree, draining the host
        entries — stale-generation cleanup on the admission path (a node
        whose entry predates a kv.reset must never restore)."""
        top.parent.drop_child(top)
        stack = [top]
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            if nd.host_id is not None:
                self.kv.drop_host_page(nd.host_id, reason="drain")
                nd.host_id = None
            nd.page = -1
            self.n_nodes -= 1

    def _try_spill(self, victim: "_Node") -> bool:
        """Offer a device-eviction victim to the host tier.  Makes budget
        room first by dropping LRU HOST leaves (the walk per drop is fine:
        one spill displaces at most a page's worth — typically one leaf —
        and pressure paths are admission-boundary, not per-token)."""
        kv = self.kv
        if self._spill_inhibit or kv.spill_bytes_budget <= 0 or \
                kv.page_nbytes > kv.spill_bytes_budget:
            return False
        while kv.host_bytes + kv.page_nbytes > kv.spill_bytes_budget:
            leaves = self._host_leaves()
            assert leaves, "host tier non-empty but no HOST leaf found"
            self._drop_host_node(min(leaves, key=lambda n: n.last_use))
        page = victim.page
        hid = kv.spill_page(page)
        if hid is None:
            return False
        victim.host_id = hid
        victim.page = -1
        self.flight.record("spill", page=int(page),
                           host_pages=kv.host_page_count,
                           host_bytes=kv.host_bytes)
        return True

    def evict_for(self, n_pages: int) -> int:
        """Reclaim up to `n_pages` DEVICE pages by walking the LRU
        eviction frontier.  Returns pages actually freed.  Wired as
        `kv.on_page_pressure`, so try_grow/COW call here before failing —
        eviction before pausing slots, preemption last resort.

        Two-level: each victim is offered to the host spill tier first
        (_try_spill — the node survives, resident HOST); only when the
        tier is off, inhibited, or can't make room does the node get
        DESTROYED — and destroying takes any HOST subtree beneath it too
        (an orphaned spilled run could never restore: the tree would no
        longer spell its prefix).  Either way one device page frees.

        One tree walk per CALL, not per freed page: the frontier goes
        into a min-heap on last_use, and a victim's parent enters the
        heap the moment it has no device children and no slot mapping —
        the multi-page reclaim an overcommitted admission needs is
        O(nodes + freed·log nodes), not O(freed·nodes), precisely when
        the pool is under the pressure eviction exists to relieve.
        Single-threaded with the allocator, so no heap entry goes stale
        mid-call; ties on last_use (never-touched nodes share 0) break by
        insertion order."""
        import heapq

        freed = 0
        heap = []
        for i, nd in enumerate(self._evictable_leaves()):
            heap.append((nd.last_use, i, nd))
        heapq.heapify(heap)
        seq = len(heap)
        while freed < int(n_pages) and heap:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            if not self._try_spill(victim):
                for ch in list(victim.children.values()):
                    self.drop_host_subtree(ch)
                parent.drop_child(victim)
                page, victim.page = victim.page, -1
                self.kv.uncache_page(page)
                self.n_nodes -= 1
                self.flight.record("prefix_evict", page=int(page),
                                   nodes_left=self.n_nodes)
            self.n_evictions += 1
            freed += 1
            if parent is not self.root and \
                    not any(c.host_id is None
                            for c in parent.children.values()) and \
                    self.kv._ref[parent.page] == 0:
                heapq.heappush(heap, (parent.last_use, seq, parent))
                seq += 1
        return freed

    # -- restore (the engine's spilled-prefix-hit admission epilogue) -------
    def promote(self, nodes, pages) -> None:
        """Re-attach freshly-restored device pages to their HOST nodes
        (kv.adopt_restored already re-marked the pages cached).  The
        engine restores a contiguous HOST path tail top-down, so every
        promoted node's ancestors are device by the end of the call —
        the residency invariant holds."""
        for nd, page in zip(nodes, pages):
            assert nd.host_id is not None
            nd.host_id = None
            nd.page = int(page)
            self._touch(nd)

    def clear(self) -> None:
        """Forget everything WITHOUT touching device-allocator state —
        pair with kv.reset(), which already drops the `_cached` marks.
        Host entries drain with the nodes that name them (a no-op after
        kv.reset, which empties the tier wholesale; load-bearing for
        set_prefix_cache(False), which must not leave orphaned host
        bytes)."""
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.host_id is not None:
                self.kv.drop_host_page(node.host_id, reason="drain")
                node.host_id = None
        self.root = _Node((), -1, None)
        self.n_nodes = 0
