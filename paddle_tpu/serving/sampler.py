"""Per-slot sampling for the continuous-batching decode step.

`lm_decode.pick_next` bakes its knobs (temperature/top_k/top_p) into the
compiled program — fine for one homogeneous batch, useless for a serving
step whose slots each carry their OWN request's knobs and rng stream.
`pick_next_per_slot` is the data-dependent twin: knobs ride [S] arrays,
every slot samples with its own key, and row s reproduces EXACTLY what

    pick_next(last[s:s+1], keys[s], temperature=t[s], top_k=k[s],
              top_p=p[s], is_probs=is_probs)

computes — same filtered support (top_k ties broken value-desc/index-asc
by the full-V `lax.top_k` sort, exactly the k-best scatter of the scalar
path; the nucleus cut is the same cumsum-minus-probs formulation with the
scalar threshold made a per-row column), and the same randomness (each
slot's `jax.random.categorical` runs under vmap on a [1, V] row with that
slot's key — bit-identical to the B=1 oracle call).  That equivalence is
what makes the serving engine's per-request exactness oracle
(tests/test_serving.py) hold for sampled decoding, not just greedy.

The MIXED prefill/decode step reuses this unchanged: the engine gathers
one logits row per slot (a decode row's own logits, or — for a prompt
whose FINAL chunk ran this step — the last prompt position's row) and
samples all S slots here.  Chunk rows emit no token until their final
chunk: mid-prefill slots ride through with temperature 0 and a zero key,
so they take the greedy branch, consume no randomness, and the host
discards their output — the per-slot key schedule stays exactly
lm_generate's (key g samples token g, key 0 at the final chunk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def greedy_next(last: Array, is_probs: bool = False) -> Array:
    """[..., V] scores -> [...] int32 — THE greedy pick, shared by the
    per-slot sampler below and the serving drafters' chain rollouts
    (serving/drafter.py ModelDrafter).  One definition matters because
    ties: `jnp.argmax` breaks ties lowest-index-first, and a drafter
    whose rollout broke them differently would mispredict exactly the
    tokens the verify step then rejects — a silent accept-rate tax, not
    a correctness bug (verification is exact either way).  `is_probs`
    is accepted for interface symmetry with pick_next; log is monotonic,
    so the argmax is the same and no transform is spent."""
    del is_probs
    return jnp.argmax(last, axis=-1).astype(jnp.int32)


def pick_next_chain(last: Array, keys: Array, temperature: Array,
                    top_k: Array, top_p: Array,
                    is_probs: bool = False) -> Array:
    """[S, K, V] chain scores + per-position keys [S, K, 2] + per-slot
    knobs [S] -> [S, K] int32 — the SPECULATIVE verify step's vectorized
    accept/resample core.

    Chain position (s, i) holds the logits the target model produced at
    the slot's generation index gen[s] + i (position 0 = the regular
    next token, positions 1..k = the drafted lookahead), and samples
    with the slot's key for THAT index — so entry (s, i) is bit-equal to
    what `pick_next_per_slot` would return for slot s on the step that
    reaches generation gen[s] + i.  Acceptance then needs no separate
    resample: position i's sample IS the exact token the non-speculative
    engine would emit there (given the prefix matched), so the accepted
    prefix plus the first mismatching sample reproduce the sequential
    stream token-for-token.  Rows are independent (the per-row contract
    of pick_next_per_slot), so flattening [S, K] -> [S*K] changes
    nothing."""
    S, K, V = last.shape
    flat = pick_next_per_slot(
        last.reshape(S * K, V), keys.reshape(S * K, 2),
        jnp.repeat(temperature, K), jnp.repeat(top_k, K),
        jnp.repeat(top_p, K), is_probs=is_probs)
    return flat.reshape(S, K)


def pick_next_per_slot(last: Array, keys: Array, temperature: Array,
                       top_k: Array, top_p: Array,
                       is_probs: bool = False) -> Array:
    """[S, V] scores + per-slot keys [S, 2] / knobs [S] -> [S] int32.

    Slots with temperature <= 0 decode greedily (their key is never
    consumed); top_k <= 0 keeps the full support; top_p outside (0, 1)
    disables the nucleus cut — all per slot, all in ONE compiled program.
    """
    S, V = last.shape
    last = jnp.log(jnp.maximum(last.astype(jnp.float32), 1e-30)) \
        if is_probs else last.astype(jnp.float32)
    greedy = greedy_next(last)

    def _sampled(_):
        t_safe = jnp.where(temperature > 0.0, temperature, 1.0)
        scaled = last / t_safe[:, None]

        # per-slot top-k: the full-V descending sort has the same ordering
        # (value desc, ties index asc) as lax.top_k(scaled, k), so rank < k
        # reproduces the scalar path's exact k-best support
        vals, idxs = jax.lax.top_k(scaled, V)
        k_eff = jnp.where(top_k > 0, top_k, V)
        keep = jnp.arange(V)[None, :] < k_eff[:, None]
        filtered = jnp.full_like(scaled, -jnp.inf).at[
            jnp.arange(S)[:, None], idxs].set(
            jnp.where(keep, vals, -jnp.inf))
        scaled = jnp.where((top_k > 0)[:, None], filtered, scaled)

        # per-slot nucleus cut — lm_decode.nucleus_filter with the scalar
        # threshold broadcast per row; the (0, 1) gate selects, it does not
        # approximate (p = 1.0 must be a true no-op, not "keep prob > 0")
        order = jnp.argsort(scaled, axis=-1)[:, ::-1]
        srt = jnp.take_along_axis(scaled, order, axis=-1)
        probs = jax.nn.softmax(srt, axis=-1)
        keep_p = jnp.cumsum(probs, axis=-1) - probs < top_p[:, None]
        nuc = jnp.full_like(scaled, -jnp.inf).at[
            jnp.arange(S)[:, None], order].set(
            jnp.where(keep_p, srt, -jnp.inf))
        apply_p = jnp.logical_and(top_p > 0.0, top_p < 1.0)
        scaled = jnp.where(apply_p[:, None], nuc, scaled)

        # per-slot randomness: each row samples as its own B=1 batch under
        # its own key — the exactness contract with the per-request oracle
        sampled = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg[None, :])[0])(
            keys, scaled)
        return jnp.where(temperature > 0.0, sampled.astype(jnp.int32),
                         greedy)

    # all-greedy steps (the common serving default) skip the two full-V
    # sorts + softmax + categorical entirely — same single jit signature,
    # the cond just picks the cheap branch at run time
    return jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)
