"""Continuous-batching LM serving: paged KV cache + slot scheduler +
one compiled decode step (see serving/engine.py for the design note)."""

from paddle_tpu.serving.engine import Request, ServingEngine
from paddle_tpu.serving.paged_kv import PagedKVCache
from paddle_tpu.serving.sampler import pick_next_per_slot

__all__ = ["Request", "ServingEngine", "PagedKVCache", "pick_next_per_slot"]
