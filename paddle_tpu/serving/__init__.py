"""Continuous-batching LM serving: paged KV cache + slot scheduler +
one compiled decode step (serving/engine.py for the core design note),
fronted by a length-prefixed-JSON TCP RPC server with streaming,
deadlines, cancellation, bounded admission, and graceful drain
(serving/server.py; protocol in serving/wire.py, blocking client in
serving/client.py, CLI in tools/serve.py)."""

from paddle_tpu.serving.drafter import NgramDrafter
from paddle_tpu.serving.engine import Request, ServingEngine
from paddle_tpu.serving.paged_kv import PagedKVCache
from paddle_tpu.serving.prefix_tree import PrefixTree
from paddle_tpu.serving.sampler import pick_next_per_slot

__all__ = ["Request", "ServingEngine", "PagedKVCache", "PrefixTree",
           "NgramDrafter", "pick_next_per_slot", "ServingServer",
           "ServingClient"]


def __getattr__(name):
    # server/client import lazily: the server pulls in asyncio machinery
    # nobody batch-scoring with the bare engine needs, and keeping them out
    # of the eager path keeps `from paddle_tpu.serving import Request` light
    if name == "ServingServer":
        from paddle_tpu.serving.server import ServingServer
        return ServingServer
    if name == "ServingClient":
        from paddle_tpu.serving.client import ServingClient
        return ServingClient
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
