"""Blocking-socket client for the serving front end (serving/server.py).

Deliberately dependency-light (stdlib sockets + serving/wire.py framing —
no jax, no asyncio in the client logic itself): a deploy target or load
generator can lift this file plus wire.py, rewriting the one package
import.  One connection multiplexes many requests: `submit()`
fires a generate, `collect()` routes the interleaved token/done frames
back per request, `cancel()` can be sent while streams are in flight —
which is exactly the shape tests/test_server.py and tools/serve.py's
--client mode drive.

>>> with ServingClient(host, port) as c:
...     toks, reason = c.generate([2, 7, 9], max_new=16, eos_id=3)
"""

from __future__ import annotations

import random
import socket
import time
from typing import Callable, Optional

from paddle_tpu.serving import wire

#: connect() errors worth retrying: the server is restarting (rolling
#: restart's SIGTERM→rebind window shows as ECONNREFUSED — an immediate,
#: cheap failure) or shed the half-open connection (reset/abort).
#: Deliberately NOT the generic OSError (a bad hostname or unroutable
#: address must fail fast) and NOT TimeoutError: a SYN-blackholed host
#: already burned the FULL I/O timeout discovering nothing — retrying
#: would multiply that by the attempt count.
_RETRYABLE_CONNECT = (ConnectionRefusedError, ConnectionResetError,
                      ConnectionAbortedError)


#: what each hello role is served by — used to make a wrong-port connect
#: name BOTH ends instead of failing with a generic frame error
_ROLE_TOOLS = {"replica": "a serving replica (tools/serve.py)",
               "router": "a fleet router (tools/fleet_router.py)",
               "pserver": "a parameter server (tools/pserver.py)"}


def _role_desc(role) -> str:
    return _ROLE_TOOLS.get(role, f"an unknown peer (role {role!r})")


def connect_with_backoff(host: str, port: int, timeout: float,
                         attempts: int = 5, backoff_s: float = 0.05,
                         backoff_max_s: float = 2.0,
                         jitter: Optional[random.Random] = None,
                         expect_role: Optional[str] = None):
    """create_connection with bounded jittered exponential backoff on
    ECONNREFUSED/reset — a replica mid-rolling-restart must not surface
    as an instant client failure.  `attempts` caps the total tries; the
    final failure re-raises the last connect error with an actionable
    message (same OSError family, so existing `except OSError` callers
    keep working).

    `expect_role` additionally runs the `hello` handshake on the fresh
    socket and verifies the peer's advertised role ("replica" / "router"
    / "pserver") — a wrong-port connect (e.g. a trainer pointed at a
    serving replica) then fails with an error NAMING both roles instead
    of a generic frame error several RPCs later.  With `expect_role`
    set, returns `(socket, hello_reply)`; without, the bare socket."""
    attempts = max(1, int(attempts))
    jitter = jitter or random.Random()
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    sock: Optional[socket.socket] = None
    for i in range(attempts):
        if i:
            # full jitter on an exponential base: concurrent clients
            # retrying a restarting server must not stampede in lockstep
            delay = min(backoff_max_s, backoff_s * (2.0 ** (i - 1)))
            time.sleep(delay * (0.5 + 0.5 * jitter.random()))
        try:
            sock = socket.create_connection((host, port), timeout=timeout)
            break
        except _RETRYABLE_CONNECT as e:
            last = e
    if sock is None:
        waited = time.monotonic() - t0
        raise type(last)(
            f"connect to {host}:{port} failed after {attempts} attempts "
            f"over {waited:.1f}s ({type(last).__name__}: {last}) — the "
            f"server is down, still binding after a restart, or the "
            f"address is wrong; raise ServingClient(connect_attempts=...) "
            f"if its restart drain takes longer than the backoff window"
        ) from last
    if expect_role is None:
        return sock
    try:
        wire.write_frame_sync(sock, {"type": "hello"})
        reply = wire.read_frame_sync(sock)
    except (wire.FrameError, OSError) as e:
        sock.close()
        raise ConnectionError(
            f"connected to {host}:{port} expecting "
            f"{_role_desc(expect_role)}, but the hello handshake failed "
            f"({type(e).__name__}: {e}) — the far end does not speak the "
            f"{wire.PROTO_DESC}") from e
    if reply is None:
        sock.close()
        raise ConnectionError(
            f"connected to {host}:{port} expecting "
            f"{_role_desc(expect_role)}, but the peer closed the "
            f"connection on the hello handshake")
    role = reply.get("role")
    if role != expect_role:
        sock.close()
        raise ConnectionError(
            f"{host}:{port} is {_role_desc(role)}, not the expected "
            f"{_role_desc(expect_role)} — check the address/port "
            f"(hello reply: proto={reply.get('proto')}, role={role!r})")
    return sock, reply


class OverloadError(RuntimeError):
    """Server refused admission (bounded queue full, or draining)."""

    def __init__(self, msg: dict):
        super().__init__(f"server overloaded: {msg.get('reason', '?')} "
                         f"(inflight={msg.get('inflight')}, "
                         f"max={msg.get('max_inflight')})")
        self.info = msg


class ServerError(RuntimeError):
    """Server answered a request with an error frame."""


class ServingClient:
    def __init__(self, host: str, port: int, timeout: float = 300.0,
                 connect_attempts: int = 5, connect_backoff_s: float = 0.05,
                 connect_backoff_max_s: float = 2.0):
        self.sock = connect_with_backoff(
            host, port, timeout, attempts=connect_attempts,
            backoff_s=connect_backoff_s, backoff_max_s=connect_backoff_max_s)
        self._next_id = 0
        # frames that arrived while collect() was routing for OTHER ids
        # (e.g. a stats reply read mid-stream) are buffered, never dropped
        self._pending: list[dict] = []

    # -- context management ------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- low-level frames --------------------------------------------------
    def send(self, msg: dict) -> None:
        wire.write_frame_sync(self.sock, msg)

    def recv(self) -> dict:
        if self._pending:
            return self._pending.pop(0)
        msg = wire.read_frame_sync(self.sock)
        if msg is None:
            raise ConnectionError("server closed the connection")
        return msg

    def _route(self, match: Callable[[dict], bool]) -> dict:
        """Return the next frame for which match(msg) is true.  Non-matching
        frames stay in _pending (in arrival order) for later calls: the
        buffer is scanned ONCE per invocation, then we fall through to the
        socket — so a backlog of other requests' frames can never starve
        the socket read."""
        for i, msg in enumerate(self._pending):
            if match(msg):
                return self._pending.pop(i)
        while True:
            msg = wire.read_frame_sync(self.sock)
            if msg is None:
                raise ConnectionError("server closed the connection")
            if match(msg):
                return msg
            self._pending.append(msg)           # someone else's frame

    # -- request lifecycle -------------------------------------------------
    def submit(self, prompt, max_new: int = 32, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 0.0, eos_id: int = -1,
               seed: Optional[int] = None, timeout_s: Optional[float] = None,
               stream: bool = True, req_id=None,
               trace: Optional[dict] = None,
               prefill_only: bool = False,
               push_to: Optional[dict] = None):
        """Fire one generate; returns the request id (auto-assigned unless
        given).  Does NOT wait — pair with collect().  `trace`
        ({"trace_id": ..., "parent": ...?}) threads a client-originated
        distributed-trace context through the router/replica spans
        (docs/observability.md "Distributed tracing").  `prefill_only`
        (+ `push_to={"host", "port"}`) is the disaggregated-prefill
        control frame the fleet router normally originates: prefill the
        prompt, kv_push the committed pages to `push_to`, report the push
        outcome on the done frame (docs/serving.md)."""
        if req_id is None:
            req_id = f"q{self._next_id}"
            self._next_id += 1
        msg = {"type": "generate", "id": req_id,
               "prompt": [int(t) for t in prompt],
               "max_new": int(max_new), "stream": bool(stream)}
        if prefill_only:
            msg["prefill_only"] = True
            if push_to is not None:
                msg["push_to"] = {"host": str(push_to["host"]),
                                  "port": int(push_to["port"])}
        if trace is not None:
            msg["trace"] = dict(trace)
        if temperature:
            msg["temperature"] = float(temperature)
        if top_k:
            msg["top_k"] = int(top_k)
        if top_p:
            msg["top_p"] = float(top_p)
        if eos_id != -1:
            msg["eos_id"] = int(eos_id)
        if seed is not None:
            msg["seed"] = int(seed)
        if timeout_s is not None:
            msg["timeout_s"] = float(timeout_s)
        self.send(msg)
        return req_id

    def cancel(self, req_id) -> None:
        """Client-initiated cancellation; the stream's final frame will be
        `done` with reason "cancelled" (or whatever finished it first)."""
        self.send({"type": "cancel", "id": req_id})

    def collect(self, req_ids, on_token: Optional[Callable] = None) -> dict:
        """Route frames until every id in `req_ids` reached its terminal
        frame.  Returns {req_id: {"tokens": [...], "reason": str,
        "stream": [token ids in arrival order]}}.  `on_token(req_id,
        token, index)` observes streaming tokens as they arrive.  Raises
        OverloadError / ServerError on those terminal frames."""
        want = set(req_ids)
        out = {rid: {"tokens": None, "reason": None, "stream": [],
                     "timing": None} for rid in want}
        mine = ("token", "done", "overload", "error")
        while any(out[rid]["reason"] is None for rid in want):
            msg = self._route(lambda m: m.get("id") in want
                              and m.get("type") in mine)
            rid = msg["id"]
            t = msg["type"]
            if t == "token":
                out[rid]["stream"].append(int(msg["token"]))
                if on_token is not None:
                    on_token(rid, int(msg["token"]), int(msg["index"]))
            elif t == "done":
                out[rid]["tokens"] = list(msg["tokens"])
                out[rid]["reason"] = msg["reason"]
                # per-request latency attribution (queue/prefill/decode/
                # replay ms + preempt/spec counts; the router adds its
                # hop/retry fields) — docs/serving.md "Message schemas"
                out[rid]["timing"] = msg.get("timing")
                # disaggregated prefill: a prefill_only done frame carries
                # the kv_push outcome (push_ok / pushed_pages / push_error)
                for k in ("push_ok", "pushed_pages", "push_error"):
                    if k in msg:
                        out[rid][k] = msg[k]
            elif t == "overload":
                raise OverloadError(msg)
            else:
                raise ServerError(msg.get("error", "unknown server error"))
        return out

    def generate(self, prompt, on_token: Optional[Callable] = None,
                 **kw) -> tuple[list, str]:
        """Submit one request and wait it out: (tokens, reason).  `tokens`
        is prompt + generated, exactly lm_generate's layout."""
        rid = self.submit(prompt, **kw)
        res = self.collect([rid], on_token=on_token)[rid]
        return res["tokens"], res["reason"]

    # -- ops ----------------------------------------------------------------
    def stats(self, stale_ok: bool = False) -> dict:
        """The server's stats RPC (queue/slot/page occupancy, latency
        percentiles).  Safe to call with streams in flight: interleaved
        token frames are buffered for the next collect().

        Default: the engine half of the snapshot is built between steps
        on the pump thread — mutually consistent (`"consistent": true`).
        `stale_ok=True` answers immediately from the server's loop thread
        without waiting on the pump — the watchdog path, which also works
        against a wedged engine (watch `pump_last_step_age_s`)."""
        msg = {"type": "stats"}
        if stale_ok:
            msg["stale_ok"] = True
        self.send(msg)
        return self._route(lambda m: m.get("type") == "stats")

    def metrics(self, aggregate: bool = False) -> str:
        """The server's Prometheus-style text exposition (the `metrics`
        frame; answered on the loop thread, readable even while the
        engine pump is wedged).  Against a fleet router,
        `aggregate=True` asks for the FLEET view: the router's own
        fleet_* rows plus every reachable replica's serving_* families
        relabeled with `replica="rN"` — one scrape endpoint for the
        whole fleet.  Metric reference: docs/observability.md."""
        msg = {"type": "metrics"}
        if aggregate:
            msg["aggregate"] = True
        self.send(msg)
        return self._route(lambda m: m.get("type") == "metrics")["text"]

    def trace(self, pings: int = 3, enable: Optional[bool] = None) -> dict:
        """Pull the server's span-ring snapshot (the `trace` RPC —
        answered on the loop thread, so it works against a wedged pump)
        and measure this connection's clock offset: `pings` ping round
        trips estimate the minimum RTT, and the reply's perf_counter
        sample midpoints to `offset_s` with local ≈ remote + offset —
        what trace_dump --merge/--pull uses to align process tracks.
        `enable` flips the server's tracing LIVE before the snapshot
        (True to start tracing a running replica without a restart,
        False to stop and collect what it froze).  Returns the reply
        frame plus `offset_s`/`rtt_s`."""
        rtts = []
        for _ in range(max(1, int(pings))):
            t0 = time.perf_counter()
            self.ping()
            rtts.append(time.perf_counter() - t0)
        rtt = min(rtts)
        rid = f"trace{self._next_id}"
        self._next_id += 1
        msg = {"type": "trace", "id": rid}
        if enable is not None:
            msg["enable"] = bool(enable)
        t_send = time.perf_counter()
        self.send(msg)
        msg = self._route(lambda m: m.get("type") in ("trace", "error")
                          and m.get("id") == rid)
        if msg["type"] == "error":
            raise ServerError(msg.get("error", "trace pull failed"))
        remote = (msg.get("clock") or {}).get("perf_counter")
        msg["rtt_s"] = rtt
        msg["offset_s"] = ((t_send + rtt / 2.0) - float(remote)
                           if remote is not None else 0.0)
        return msg

    def history(self, last_s: Optional[float] = None,
                names=None, aggregate: bool = False) -> dict:
        """Pull the server's metric time-series ring (the `history` RPC —
        loop thread, stale-ok: answers against a wedged pump, exactly
        when the trailing window matters).  `last_s` trims each series
        to the trailing window, `names` filters series keys by prefix.
        Against a fleet router, `aggregate=True` asks for the FLEET
        view: the router's own series plus every reachable replica's
        relabeled `replica="rN"` — what tools/obs_top.py renders.
        Returns the reply frame: ring accounting + {"series": {key:
        {"kind", "points": [[unix_ts, value], ...]}}}."""
        rid = f"hist{self._next_id}"
        self._next_id += 1
        msg = {"type": "history", "id": rid}
        if last_s is not None:
            msg["last_s"] = float(last_s)
        if names is not None:
            msg["names"] = [str(n) for n in names]
        if aggregate:
            msg["aggregate"] = True
        self.send(msg)
        msg = self._route(lambda m: m.get("type") in ("history", "error")
                          and m.get("id") == rid)
        if msg["type"] == "error":
            raise ServerError(msg.get("error", "history pull failed"))
        return msg

    def dump(self) -> dict:
        """Ask the server to freeze a postmortem bundle NOW (answered on
        the loop thread — works against a wedged or dead engine pump).
        Returns {"path", "events", "spans"}; raises ServerError when the
        server has no postmortem directory configured.  Pretty-print the
        bundle with `python tools/postmortem.py <path>`."""
        # the dump gets its own id (the server echoes it on both reply
        # types): matching bare `error` frames would steal another
        # request's terminal error on a multiplexed connection — e.g. a
        # generate failed by a dying pump, exactly the scenario dump()
        # is advertised for
        rid = f"dump{self._next_id}"
        self._next_id += 1
        self.send({"type": "dump", "id": rid})
        msg = self._route(lambda m: m.get("type") in ("dump", "error")
                          and m.get("id") == rid)
        if msg["type"] == "error":
            raise ServerError(msg.get("error", "dump failed"))
        return {k: msg[k] for k in ("path", "events", "spans") if k in msg}

    def hello(self) -> dict:
        """Version/capabilities negotiation: the server's `hello` reply
        (`proto`, `role` — "replica" for an engine-pump server, "router"
        for the fleet front tier — `capabilities`, and sizing facts like
        `page_size`/`max_inflight`).  Safe mid-stream: interleaved frames
        are buffered like every other RPC."""
        self.send({"type": "hello"})
        return self._route(lambda m: m.get("type") == "hello")

    def ping(self) -> bool:
        self.send({"type": "ping"})
        self._route(lambda m: m.get("type") == "pong")
        return True
