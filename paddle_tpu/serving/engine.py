"""Continuous-batching LM decode engine.

The batch-at-a-time `lm_decode.lm_generate` compiles per (B, P, max_new)
shape and always runs max_new steps; mixed-length production traffic either
pads everything to the worst case or recompiles constantly.  This engine is
the serving answer (the slot configuration studied in arXiv:2605.25645):

  * a fixed set of S decode SLOTS, each holding at most one in-flight
    request; the decode step is ONE jitted function of fixed shape, compiled
    once and reused for the whole workload — freed slots refill mid-flight,
    so the chip never waits for the longest request of a batch;
  * KV context lives in the paged pool (serving/paged_kv.py) behind
    per-slot page tables — HBM proportional to tokens actually held;
  * prompts PREFILL in fixed-size CHUNKS processed INSIDE the regular
    step (the mixed prefill/decode shape of arXiv:2604.15464): decode
    rows and prompt-chunk rows pack into one ragged [max_step_tokens]
    dispatch, so a cold multi-thousand-token prompt no longer stalls
    every decoding slot's inter-token latency behind its own prefill
    program, and the per-step token budget bounds p99 inter-token
    latency by construction.  Chunk count derives from prompt length —
    any prompt the page pool can hold is admissible, no bucket ceiling.
    `prefill_chunk=None` restores the legacy whole-prompt bucketed
    prefill dispatches (`data/feeder._bucket_len`) — the A/B baseline;
  * per-slot rng streams and sampling knobs are preserved EXACTLY: request
    r's tokens are identical to `lm_generate(..., use_cache=True)` run on r
    alone (same rng key schedule, same sampler semantics via
    serving/sampler.py, same eos early-stop) — the oracle contract
    tests/test_serving.py enforces token-for-token.

Scheduling is a host loop (numpy metadata, device pools): admit from the
FIFO queue into free slots, run one compiled step over all S slots, retire
finished slots, repeat.  A slot that cannot get its next page (overcommitted
pool) is PAUSED — excluded from that step's key consumption and token
banking — and resumes bit-identically once a page frees, because its key
schedule is indexed by its own generation counter, not by wall-clock steps.

ENGINE STATE AS A PYTREE: everything the compiled steps read or write is an
explicit, jittable `EngineState` — the per-layer KV page pools, the page
table (with the mixed step's virtual trash row), and the per-slot
pos/last-token/generation/rng-key/sampling-knob arrays, all device-resident
with donated in/out buffers so pools and slot arrays update IN PLACE.  The
steps are pure functions (state, run-mask) -> (state', next-tokens): pos,
gen and last-token advance ON DEVICE for the slots the run mask marks, and
each slot's sampling key is state.keys[s, gen[s]] — so a steady pure-decode
run re-stages NOTHING from the host.  All host-side scheduling (allocator,
prefix tree, preemption, admission) mutates host mirrors that sync to the
pytree only at boundaries: a page-table write bumps `PagedKVCache.version`,
a slot lifecycle event (admit/retire/preempt/abort/restore) sets the
slots-dirty flag, and the run mask re-uploads only when its membership
changes.  `n_host_stages` counts every host->device staging transfer —
tests/test_engine_state.py asserts it stays flat across pure-decode steps.
The same pytree is the serving checkpoint/restore + fleet-migration unit:
`checkpoint_state()` / `restore_state()` freeze and resume an engine
MID-FLIGHT (queued + decoding + mid-chunk slots) bit-exactly.

SPECULATIVE DECODING (`spec_k > 0`): decode is one token per step per slot
— the dispatch rate is the throughput ceiling.  The speculative path lifts
it without changing a single emitted token: a host-side DRAFTER
(serving/drafter.py — prompt-lookup n-grams over the slot's own committed
tokens by default, pluggable for a small draft model) proposes up to k
tokens per decoding slot, and the target model scores ALL k+1 positions
per slot in ONE ragged dispatch (the verify step — the PR 8 packed-row
machinery pointed at the future instead of the prompt).  Draft K/V is
written optimistically; every chain position samples with the slot's OWN
key for that generation index (`keys[s, gen+i]` — sampler.py
`pick_next_chain`), so position i's sample IS the token the sequential
engine would emit there, and acceptance is exact by construction: the
emitted stream is the accepted draft prefix plus the first mismatching
sample — token-for-token identical to spec-off across greedy/top-k/
nucleus/full sampling, prefix hits, chunked mixed steps, preempt/replay
and tensor parallelism (the rejection-sampling equivalence degenerates to
prefix agreement once the randomness is a fixed per-slot key schedule).
Rollback: rejected-suffix K/V on device needs NO cleanup (causally masked
now, overwritten before it could ever be attended); the host returns the
unjustified tail pages via `kv.uncommit_tail` — the same page-granular
rollback preempt/replay already exercises.  Chunk rows coexist with spec
chains under the same token budget (mode-aware packing), and the compiled
set stays bounded: ONE verify signature per budget next to the one decode
+ one mixed signature.  `set_speculation()` is the idle A/B toggle.

TENSOR-PARALLEL DECODE (`mesh=` with a `model` axis of size > 1): attention
heads and the per-layer KV pools partition over the mesh's `model` axis —
w_q/w_k/w_v column-shard, the pools shard on their kv-head axis, w_o
row-shards so the out-projection's partial sums meet in ONE all-reduce per
layer (the Megatron split), and everything else (page tables, slot arrays,
non-attention params, logits, sampling) stays replicated.  The paged
attention core runs under shard_map (ops/attention.py), so the pools are
NEVER all-gathered — each device reads and writes only its head shard
(tools/hlo_shard_check.py proves it on the lowered HLO).  One replica then
serves a model larger than a chip's HBM and decodes with every chip's
FLOPs, still through ONE compiled decode signature.
"""

from __future__ import annotations

import time
from collections import deque
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.data.feeder import _bucket_len
from paddle_tpu.graph.context import TEST
from paddle_tpu.graph.lm_decode import (_is_probs, _resolve_io_names,
                                        init_kv_caches, pick_next)
from paddle_tpu.obs.compile_watch import get_compile_watch
from paddle_tpu.obs.flight import get_flight_recorder
from paddle_tpu.obs.trace import get_tracer
from paddle_tpu.parallel.mesh import MODEL_AXIS, axis_size
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.serving.paged_kv import PagedKVCache
from paddle_tpu.serving.prefix_tree import PrefixTree
from paddle_tpu.serving.sampler import pick_next_chain, pick_next_per_slot

# Dynamic-speculation policy constants (see ServingEngine._dyn_k).
# _EWMA_ALPHA weights the newest chain's accept rate into the slot's
# running estimate — 0.25 adapts within ~4 chains without thrashing on a
# single unlucky draft.  _PROBE_EVERY paces the k=1 re-probe of a slot
# whose depth decayed to 0: often enough to notice a workload turning
# repetitive, rare enough that a hostile workload pays ~1/16th of a
# wasted verify row per window.
_EWMA_ALPHA = 0.25
_PROBE_EVERY = 16


class EngineState(NamedTuple):
    """The decode/mixed steps' ENTIRE device state — one jittable pytree.

    Donated into every compiled step and rebound from its output, so pools
    and slot arrays update in place (no copies, no stale aliases).  Under
    tensor parallelism the pools shard on their kv-head axis over the mesh
    `model` axis; every other leaf is replicated."""

    pools: dict      # {layer: {"k"/"v": [num_pages, page_size, h_kv, dh]}}
    table: jax.Array  # [S+1, pages_per_slot] int32 — row S is the mixed
                      # step's virtual all-trash row (always zeros)
    pos: jax.Array    # [S] int32 tokens resident in the paged cache
    toks: jax.Array   # [S] int32 last emitted token (decode-step input)
    gen: jax.Array    # [S] int32 tokens emitted — indexes `keys`
    keys: jax.Array   # [S, capacity_tokens, 2] uint32 per-slot key schedule
    temp: jax.Array   # [S] float32 sampling temperature
    topk: jax.Array   # [S] int32
    topp: jax.Array   # [S] float32


class Request:
    """One generation request — the per-row knobs of `lm_generate`."""

    def __init__(self, req_id, prompt_ids, max_new: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int = -1, rng=None,
                 deadline: Optional[float] = None,
                 trace: Optional[dict] = None):
        self.req_id = req_id
        # inbound distributed-trace context ({"trace_id": ..., "parent":
        # ...}, normally stamped by the fleet router at ingress): the
        # engine's lifecycle spans carry it as attrs, so one trace_id
        # threads the request through every process it crossed
        self.trace = dict(trace) if trace else None
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_id = int(eos_id)
        # absolute time on the ENGINE's clock (engine.clock(), default
        # time.monotonic) after which the request is expired — swept at the
        # top of every step(), whether the request is queued or in flight
        self.deadline = None if deadline is None else float(deadline)
        # tokens this request had generated when it was preempted back
        # into the queue: a re-admission replays them identically (and
        # KEEPS the stash — see _admit), and a cancel/deadline that lands
        # while it waits or mid-replay must report at least them — the
        # front end already streamed them to the client
        self._preempted_gen: Optional[list] = None
        # default PRNGKey(0) — the same default lm_generate uses, so the
        # parity oracle needs no special-casing
        self.rng = jax.random.PRNGKey(0) if rng is None else rng
        if self.prompt_ids.size < 1:
            # ValueError, not assert: requests arrive off the NETWORK
            # (serving/server.py) and `python -O` strips asserts — an
            # empty prompt must never reach the pump
            raise ValueError(f"request {req_id!r}: empty prompt")
        if self.temperature <= 0.0 and (self.top_k > 0 or
                                        0.0 < self.top_p < 1.0):
            raise ValueError(
                f"top_k={self.top_k}/top_p={self.top_p} need temperature "
                f"> 0 — temperature=0 means greedy argmax, which would "
                f"silently ignore them")


class _Slot:
    """Host-side state of one occupied decode slot.

    Two modes, distinguished by `gen`: `gen == 0` is PREFILL mode — the
    slot is still committing its prompt chunk-by-chunk through the mixed
    step (`pos` = prompt tokens committed so far, nothing emitted yet);
    `gen >= 1` is DECODE mode — token 0 was sampled from the last prompt
    position's logits and the slot advances one token per step.  Legacy
    (unchunked) admission constructs the slot directly in decode mode
    with `first_tok` set."""

    __slots__ = ("req", "keys", "pos", "gen", "last_tok", "generated",
                 "admit_seq", "replay_until", "accept_ewma", "probe_tick")

    def __init__(self, req: Request, keys: np.ndarray, pos: int,
                 first_tok: Optional[int], admit_seq: int):
        self.req = req
        self.keys = keys          # [max_new, 2] uint32 — key g samples token g
        self.pos = pos            # tokens resident in the paged cache
        if first_tok is None:     # prefill mode: nothing emitted yet
            self.gen = 0
            self.last_tok = -1
            self.generated = []
        else:
            self.gen = 1          # tokens emitted so far (token 0 at admit)
            self.last_tok = first_tok  # emitted but not yet in the cache
            self.generated = [first_tok]
        self.admit_seq = admit_seq  # admission order — preemption victims
                                    # are youngest-first (least work lost)
        # tokens below this generation index are a post-preemption REPLAY
        # of already-emitted output (deduped downstream) — the lifecycle
        # trace shows them as a `replay` span, flipping to `decode` at the
        # first genuinely fresh token.  0 = never preempted / caught up.
        self.replay_until = 0
        # dynamic speculation (spec_dynamic=True): EWMA of this slot's
        # per-chain accept fraction (None = cold, no chain verified yet)
        # steers the per-slot draft depth k_s; probe_tick paces the k=1
        # re-probes a decayed-to-0 slot still gets, so a workload that
        # turns repetitive mid-request can climb back out of plain decode
        self.accept_ewma: Optional[float] = None
        self.probe_tick = 0


class ServingEngine:
    """Slot scheduler + paged KV + one compiled decode step.

    >>> eng = ServingEngine(tr.executor, tr.params, num_slots=4)
    >>> eng.add_request(Request("a", prompt, max_new=16, eos_id=2))
    >>> results = eng.run()          # {"a": np.int32 prompt+generated}
    """

    def __init__(self, executor, params, num_slots: int = 4,
                 page_size: int = 16, max_context: int = 256,
                 num_pages: Optional[int] = None,
                 input_name: Optional[str] = None,
                 logits_name: Optional[str] = None,
                 prefix_cache: bool = True,
                 spill_bytes_budget: int = 0,
                 prefill_chunk: Optional[int] = -1,
                 max_step_tokens: Optional[int] = None,
                 spec_k: int = 0, drafter=None,
                 spec_dynamic: bool = False,
                 decode_steps: int = 1,
                 decode_mode: str = "auto",
                 mesh=None, tracer=None):
        self.executor = executor
        self.input_name, self.logits_name = _resolve_io_names(
            executor.model, input_name, logits_name)
        self._probs = _is_probs(executor.model, self.logits_name)
        # tensor parallelism: a mesh whose `model` axis exceeds 1 shards
        # attention heads + KV pools over it (docs/serving.md "Sharded
        # decode").  The executor must see the same mesh — layers_attn
        # routes the paged attention core through shard_map off ctx.mesh.
        self.mesh = mesh if mesh is not None else getattr(executor, "mesh",
                                                          None)
        self.tp = axis_size(self.mesh, MODEL_AXIS)
        self._repl_sharding = None
        self._param_shardings_tree = None
        self._tp_ffn_pairs: list = []
        self._tp_lm_head: Optional[str] = None
        if self.tp > 1:
            if executor.mesh is not None and executor.mesh is not self.mesh:
                raise ValueError(
                    "ServingEngine(mesh=...) conflicts with the executor's "
                    "own mesh — build the executor meshless (or with the "
                    "same mesh) for tensor-parallel serving")
            executor.mesh = self.mesh
            from jax.sharding import NamedSharding, PartitionSpec
            self._repl_sharding = NamedSharding(self.mesh, PartitionSpec())
            self._validate_tp(executor.model)
            # params placed ONCE: attention projections sharded (w_q/w_k/
            # w_v by column = head, w_o by row), everything else
            # replicated — the tree is reused verbatim as the compiled
            # steps' in_shardings, so placement and jit can never diverge
            self._param_shardings_tree = self._tp_param_shardings(params)
            params = jax.device_put(params, self._param_shardings_tree)
        self.params = params
        pages_per_slot = -(-int(max_context) // int(page_size))
        self.kv = PagedKVCache(executor, num_slots, page_size,
                               pages_per_slot, num_pages,
                               mesh=self.mesh if self.tp > 1 else None,
                               spill_bytes_budget=spill_bytes_budget)
        # the ONE canonical pool sharding, derived by the cache that owns
        # the pools — every jit that hands pools back pins to it
        self._pool_sharding = self.kv.pool_sharding
        # prefix caching (serving/prefix_tree.py): retired requests donate
        # their fully-committed pages to a radix index keyed on token-id
        # runs; admission walks it and prefills ONLY the uncached suffix.
        # Sharing is entirely host-side allocator/table state — the decode
        # step's one compiled signature is untouched.  The tree's LRU
        # eviction is the allocator's page-pressure hook, so cached
        # prefixes are reclaimed BEFORE slots pause or preempt.
        self.prefix: Optional[PrefixTree] = \
            PrefixTree(self.kv) if prefix_cache else None
        if self.prefix is not None:
            self.kv.on_page_pressure = self.prefix.evict_for
        self.n_prefix_hits = 0
        self.n_prefix_misses = 0
        self.prefill_tokens_saved = 0
        # KV spill tier admission accounting (the page-level counters —
        # n_spilled/n_restored/host_bytes — live on the kv allocator):
        # hits whose prefix needed a host->device restore, and the
        # prefill tokens among `C` served from restored pages — the
        # number kv.n_restored * page_size must bound (the bench's
        # restored-vs-saved reconciliation)
        self.n_restore_hits = 0
        self.restore_tokens_saved = 0
        # cross-replica kv transfer plane (docs/serving.md "Disaggregated
        # prefill/decode"): mounts = import_prefix calls that attached at
        # least one run; pages count what came over the wire (the
        # byte-level n_exported/n_imported live on the kv allocator)
        self.n_kv_mounts = 0
        self.kv_pages_mounted = 0
        self.queue: deque[Request] = deque()
        self.slots: list[Optional[_Slot]] = [None] * num_slots
        # finished-but-uncollected outputs: run() POPS what completed on
        # its watch, so a long-lived engine does not accumulate results
        self.results: dict = {}
        # req_id -> why it finished: "stop" (eos) / "length" (max_new) /
        # "cancelled" / "deadline" — popped alongside results in run()
        self.finish_reasons: dict = {}
        # request-lifecycle hooks for a front end driving step() directly
        # (serving/server.py): on_token(req_id, token, index) fires for
        # every emitted token (index 0 = the prefill-sampled token),
        # on_finish(req_id, tokens, reason) once per request.  Both run on
        # the thread calling step() — keep them cheap.  A preempted request
        # REPLAYS its (identical) tokens from index 0 on re-admission:
        # streaming consumers must dedup by index (server.py does).
        self.on_token = None
        self.on_finish = None
        # the deadline clock — injectable so tests can expire requests
        # deterministically (e.g. clock = lambda: engine.n_decode_steps)
        self.clock = time.monotonic
        # request-lifecycle tracing (paddle_tpu/obs): spans are recorded
        # ONLY while tracer.enabled — every emission site checks first, so
        # the disabled cost is one attribute read.  All spans record on
        # the step()-driving thread (the pump), matching the tracer's
        # single-writer ring contract.  `tracer=` lets an embedder (or an
        # in-process test fleet) give each engine its own ring, so a
        # per-process `trace` RPC snapshot stays per-process.
        self.tracer = tracer if tracer is not None else get_tracer()
        self._obs_open: dict = {}   # req_id -> open span handle (one phase
                                    # open per request at any moment)
        self._req_trace: dict = {}  # req_id -> inbound trace context
        # per-request latency attribution (ALWAYS on — the phase
        # transitions below are a handful of clock reads per request
        # LIFECYCLE, never per token, so there is no flag to forget):
        # _req_phase holds the open phase, _req_attr the per-phase wall
        # accumulators + occurrence counters; _finish folds them into
        # finish_timing[req_id] — the `done` frame's `timing` breakdown
        # (docs/serving.md), popped by the server/run() like results.
        self._req_phase: dict = {}
        self._req_attr: dict = {}
        self.finish_timing: dict = {}
        # black box (obs/flight.py): request-lifecycle transitions recorded
        # when the front end (or a test) enables the process-global
        # recorder — events are per-request, never per-token, so the
        # disabled AND enabled costs both stay off the token hot path
        self.flight = get_flight_recorder()
        self.n_decode_steps = 0
        self.n_preemptions = 0
        self.n_cancelled = 0
        self.n_expired = 0
        self.tokens_generated = 0
        self.occupancy_sum = 0.0              # sum of live/S over steps
        self._admit_seq = 0
        self._prefill_cache: dict[int, object] = {}
        self._pack_cache: dict[int, object] = {}
        # prefix-hit compiled pieces: suffix prefill keyed on (prefix
        # pages, suffix bucket), offset pack keyed on suffix bucket — the
        # matched token count and in-page offset stay DYNAMIC operands, so
        # signatures are bounded by (pages_per_slot x buckets), never by
        # distinct prefix lengths
        self._prefix_prefill_cache: dict[tuple, object] = {}
        self._prefix_pack_cache: dict[int, object] = {}
        # -- device-resident EngineState + its host sync machinery --------
        # The compiled steps advance pos/gen/toks on device, so the hot
        # path re-stages NOTHING: the page table re-uploads only when a
        # host-side table write bumps kv.version, the per-slot arrays only
        # when a slot lifecycle event sets _slots_dirty, and the run mask
        # only when its membership changes.  n_host_stages counts every
        # host->device transfer (the test_engine_state.py regression).
        self.n_host_stages = 0
        S = num_slots
        self._kk = self.kv.capacity_tokens     # keys per slot (> max_new)
        self._kv_synced = -1                   # kv.version last uploaded
        self._slots_dirty = True
        self._run_host: Optional[np.ndarray] = None
        self._d_run = None
        self._d_table = self._d_pos = self._d_toks = self._d_gen = None
        self._d_keys = self._d_temp = self._d_topk = self._d_topp = None
        self._d_eos = self._d_maxnew = None
        # every engine jit reports to the compile watcher (obs/
        # compile_watch.py): the decode step must stay at ONE signature,
        # per-bucket prefill compiles feed the recompile-storm detector
        dec_jit = jax.jit(self._decode_impl, donate_argnums=(1,),
                          **self._step_sharding_kwargs(n_extra=1))
        self._decode_step = get_compile_watch().wrap_jit(
            "serving.decode_step", dec_jit)
        # CHUNKED PREFILL (mixed prefill/decode steps): prompts commit in
        # `prefill_chunk`-token chunks INSIDE the regular step — decode
        # rows and chunk rows pack into one ragged [max_step_tokens] row
        # list (ops/attention.py:ragged_paged_attention_step), so a long
        # cold prompt can no longer stall every decoding slot behind its
        # own prefill dispatch, and the per-step token budget bounds p99
        # inter-token latency BY CONSTRUCTION under adversarial prompt
        # mixes.  Compiled signatures: the [S,1] decode step (pure-decode
        # steps keep it) + ONE mixed-step signature per max_step_tokens
        # value.  prefill_chunk=None disables chunking (legacy bucketed
        # whole-prompt prefill); -1 (the default) picks 4*page_size.
        mix_jit = jax.jit(self._mixed_impl, donate_argnums=(1,),
                          **self._step_sharding_kwargs(n_extra=6))
        self._mixed_step = get_compile_watch().wrap_jit(
            "serving.mixed_step", mix_jit)
        self.prefill_chunk: Optional[int] = None
        self.max_step_tokens = 0
        self.set_chunking(4 * self.kv.page_size if prefill_chunk == -1
                          else prefill_chunk, max_step_tokens)
        self.n_prefill_chunks = 0
        self.n_mixed_steps = 0
        # SPECULATIVE DECODING (the verify step): ONE extra compiled
        # signature per (token budget, spec_k) — created lazily like the
        # others, compiled only when speculation is actually on.  The
        # drafter runs on the host between steps; the verify step scores
        # every slot's k+1-position chain (plus any prefill chunk rows)
        # in one ragged dispatch and computes acceptance ON DEVICE, so
        # pos/gen advance by the accepted length without a host round
        # trip inside the step.
        spec_jit = jax.jit(self._spec_impl, donate_argnums=(1,),
                           **self._step_sharding_kwargs(n_extra=9,
                                                        n_out=2))
        self._spec_step = get_compile_watch().wrap_jit(
            "serving.spec_step", spec_jit)
        self.spec_k = 0
        self.drafter = None
        self.spec_dynamic = False
        self._drafter_takes_eos = False
        self.n_spec_steps = 0       # verify dispatches run
        self.n_spec_chains = 0      # (slot, step) chains that emitted
        self.n_spec_drafted = 0     # draft tokens scored by the target
        self.n_spec_accepted = 0    # draft tokens that matched exactly
        self.n_spec_tokens = 0      # tokens banked through chains —
                                    # == accepted + chains unless an eos
                                    # truncated a chain (reconciliation)
        self.n_draft_steps = 0      # draft passes that proposed anything
        self.set_speculation(spec_k, drafter, dynamic=spec_dynamic)
        # MULTI-STEP DECODE (the scanned step): when every live slot is in
        # pure-decode mode, step() runs ONE jitted lax.scan of
        # `decode_steps` identical per-step bodies over the donated
        # EngineState — pos/gen/toks/KV writes advance on device for up to
        # k tokens per dispatch, eos/max_new enforced by an on-device run
        # mask INSIDE the scan (a finished slot's later iterations become
        # no-ops, mirroring lm_generate's early-exit chunks), and the host
        # unpacks a [k, S] token block at the boundary where admission,
        # streaming, cancel/deadline sweeps, and preemption still happen.
        # Compiled signatures: ONE scanned program per (S, k) — k is a
        # static argument of one lazily-built jit, alongside the k=1 step
        # (which mixed/spec steps and page-starved windows fall back to).
        # Tokens are bit-identical to k=1: the body IS _decode_impl and
        # the device mask mirrors _bank_token's retirement rule exactly.
        self._scan_step = None
        self.decode_steps = 1
        self.n_scan_steps = 0       # scan body iterations run (k per flush)
        self.n_scan_flushes = 0     # scanned dispatches (boundaries seen)
        # tokens banked for the slot currently being unpacked arrive in a
        # burst of cur_burst (> 1 only inside a scan flush): on_token
        # consumers divide inter-arrival gaps by it so inter-token latency
        # stays honest across decode_steps settings (serving/server.py)
        self.cur_burst = 1
        self.set_decode_steps(decode_steps)
        # DISPATCH POLICY (`decode_mode`): "auto" (the default) picks the
        # best dispatch PER FLUSH WINDOW among what is configured — the
        # spec verify step when any slot drafted (or prefill chunks are
        # in flight), the k-step scan when the window is pure-decode and
        # draft-free, the mixed step otherwise — so speculation and
        # multi-step decode COMPOSE instead of excluding each other
        # (drafting happens at the scan boundary, chains verify inside
        # the verify dispatch).  "static" keeps the legacy exclusivity:
        # spec_k > 0 disables the scan entirely.  A dispatch knob like
        # decode_steps: emitted tokens are bit-identical either way.
        self.decode_mode = "auto"
        self.set_decode_mode(decode_mode)
        # token-budget observability: per-step scheduled-token histogram
        # and the pump-step gap decoding slots actually saw (ms) — the
        # HOL-blocking number chunking exists to bound.  Standalone
        # Histogram objects (obs/metrics.py shape); the server's engine
        # collector splices their samples into the metrics frame.
        from paddle_tpu.obs.metrics import Histogram as _Hist
        import threading as _threading
        self.step_tokens_hist = _Hist(
            "serving_step_tokens", "", (), _threading.Lock(),
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048))
        self.decode_gap_hist = _Hist(
            "serving_decode_gap_ms", "", (), _threading.Lock(),
            buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                     2500, 5000))
        # speculation observability: wall ms per draft pass (host lookup
        # or the batched serving.draft_step dispatch — the overhead the
        # accept rate must out-earn), and the CHOSEN per-slot draft depth
        # at every propose opportunity (the dynamic-k policy's output —
        # mass at 0 means slots degraded to plain decode)
        self.draft_ms_hist = _Hist(
            "serving_draft_ms", "", (), _threading.Lock(),
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                     250))
        self.spec_k_hist = _Hist(
            "serving_spec_k_effective", "", (), _threading.Lock(),
            buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32))
        self._t_prev_decode: Optional[float] = None

    # -- tensor-parallel sharding trees ------------------------------------
    def _validate_tp(self, model) -> None:
        """Head counts must divide over the `model` axis: each device owns
        whole query heads and whole kv heads (the shard_map attention core
        and the pool's kv-head partition both depend on it)."""
        for l in model.layers:
            if l.type != "multi_head_attention":
                continue
            heads = int(l.attrs["num_heads"])
            h_kv = int(l.attrs.get("num_kv_heads", 0) or heads)
            if heads % self.tp or h_kv % self.tp:
                raise ValueError(
                    f"layer {l.name!r}: num_heads={heads} / "
                    f"num_kv_heads={h_kv} must both divide the mesh model "
                    f"axis ({self.tp}) — tensor-parallel decode gives each "
                    f"device whole heads")

    def _tp_param_shardings(self, params) -> dict:
        """NamedSharding per parameter: attention projections partition
        over `model` (w_q/w_k/w_v by output column — whole heads per
        device; w_o by input row, so the out-projection is partial sums
        meeting in one all-reduce), the FFN pairs get the same Megatron
        column/row split (first fc by output column — its bias and the
        elementwise activation stay column-local; second fc by input
        row — one more all-reduce per layer, and the wide [dim, 4*dim]
        hidden activation never materializes whole on any device), the
        LM head row-shards (partial logits meet in one all-reduce —
        replicated logits with ZERO all-gathers, so sampling is
        untouched), and everything else is replicated.

        FFN pairs are detected structurally: an fc layer feeding
        directly into another fc layer is the Megatron pattern; the
        hidden dim must divide the mesh (skipped — left replicated —
        otherwise, same divisibility discipline as the head counts).
        `_tp_ffn_pairs` / `_tp_lm_head` record what actually sharded so
        tools/hlo_shard_check.py can derive the exact expected
        all-reduce count instead of guessing."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        col = NamedSharding(self.mesh, P(None, "model"))
        row = NamedSharding(self.mesh, P("model", None))
        sh = {name: self._repl_sharding for name in params}
        self._tp_ffn_pairs: list[tuple[str, str]] = []
        self._tp_lm_head: Optional[str] = None
        layers = {l.name: l for l in self.executor.model.layers}
        for l in layers.values():
            if l.type != "multi_head_attention":
                continue
            names = [l.inputs[i].input_parameter_name for i in range(4)]
            for n in names[:3]:                       # w_q, w_k, w_v
                sh[n] = col
            sh[names[3]] = row                        # w_o
        for l in layers.values():                     # Megatron FFN pairs
            if l.type != "fc" or len(l.inputs) != 1:
                continue
            src = layers.get(l.inputs[0].input_layer_name)
            if src is None or src.type != "fc" or len(src.inputs) != 1:
                continue
            w1 = src.inputs[0].input_parameter_name
            w2 = l.inputs[0].input_parameter_name
            hidden = int(params[w1].shape[1])
            if hidden % self.tp or sh[w1] is not self._repl_sharding \
                    or sh[w2] is not self._repl_sharding:
                continue
            sh[w1] = col                              # up-projection
            if src.bias_parameter_name:
                # the bias adds to a column-sharded activation — shard
                # its LAST axis the same way (biases are stored
                # [1, out]) so the add stays collective-free
                b = src.bias_parameter_name
                sh[b] = NamedSharding(self.mesh, P(
                    *([None] * (params[b].ndim - 1) + ["model"])))
            sh[w2] = row                              # down-projection
            # stamp the Megatron layout on the layers themselves —
            # fc_layer pins the activations (hidden stays sharded, the
            # down-projection's partial sums all-reduce immediately), so
            # GSPMD cannot trade the one clean all-reduce for a
            # reduce-scattered residual stream full of small collectives
            src.attrs["tp_out"] = MODEL_AXIS
            l.attrs["tp_out"] = "replicated"
            self._tp_ffn_pairs.append((w1, w2))
        head = layers.get(self.logits_name)           # vocab projection
        if head is not None and head.type == "fc" and \
                len(head.inputs) == 1:
            w = head.inputs[0].input_parameter_name
            if int(params[w].shape[0]) % self.tp == 0 and \
                    sh[w] is self._repl_sharding:
                sh[w] = row
                head.attrs["tp_out"] = "replicated"
                feed_l = layers.get(head.inputs[0].input_layer_name)
                if feed_l is not None:
                    # pin the head's INPUT sharded on the contraction
                    # axis: with only the output pinned, GSPMD's cost
                    # model may satisfy it by ALL-GATHERING the weight —
                    # at production vocab the head is the largest param
                    # in the model, and reassembling it per step is the
                    # exact failure this sharding exists to prevent.  A
                    # replicated input slices locally for free, the dot
                    # goes partial, and the pinned-replicated output
                    # forces the one all-reduce.
                    feed_l.attrs["tp_out"] = MODEL_AXIS
                self._tp_lm_head = w
        if self._tp_ffn_pairs or self._tp_lm_head:
            # the residual stream and its layer norms are REPLICATED in
            # the Megatron layout — pin them, or GSPMD propagation will
            # happily shard the residual and pay partial-LN reductions
            # plus activation all-gathers at every projection input
            for l in layers.values():
                if l.type in ("layer_norm", "addto"):
                    l.attrs.setdefault("tp_out", "replicated")
        return sh

    def _state_shardings(self) -> "EngineState":
        pool = {name: {"k": self._pool_sharding, "v": self._pool_sharding}
                for name in self.kv.pools}
        r = self._repl_sharding
        return EngineState(pools=pool, table=r, pos=r, toks=r, gen=r,
                           keys=r, temp=r, topk=r, topp=r)

    def _step_sharding_kwargs(self, n_extra: int, n_out: int = 1) -> dict:
        """Explicit in/out sharding trees for the compiled steps (the
        compile_step_with_plan discipline): (params, EngineState,
        n_extra replicated operands) -> (EngineState, n_out replicated
        outputs — sampled tokens, and for the verify step the accepted
        count too).  Empty off-mesh — the single-device jits stay
        exactly as before."""
        if self.tp <= 1:
            return {}
        st = self._state_shardings()
        r = self._repl_sharding
        return {"in_shardings": (self._param_shardings_tree, st)
                + (r,) * n_extra,
                "out_shardings": (st,) + (r,) * n_out}

    def _pools_out_kwargs(self) -> dict:
        """out_shardings pinning a pool-writing jit's output to the
        canonical pool sharding (tensor-parallel only): prefill packs and
        COW copies must hand pools back in the exact layout the donated
        decode-step state expects."""
        if self.tp <= 1:
            return {}
        return {"out_shardings": {
            name: {"k": self._pool_sharding, "v": self._pool_sharding}
            for name in self.kv.pools}}

    # -- host mirror -> device pytree sync ---------------------------------
    def _stage(self, x):
        """Host -> device staging chokepoint: every upload the engine ever
        performs goes through here, so `n_host_stages` is an exact
        transfer count (the zero-restaging regression reads it) and
        tensor-parallel runs commit replicated copies up front instead of
        paying a reshard inside the step dispatch."""
        self.n_host_stages += 1
        if self._repl_sharding is not None:
            return jax.device_put(np.asarray(x), self._repl_sharding)
        return jnp.asarray(x)

    def _sync_device_state(self) -> None:
        """Re-upload exactly the device arrays whose HOST mirrors changed:
        the page table when any allocator write bumped kv.version
        (admission/COW/preempt/retire), the per-slot arrays when a slot
        lifecycle event set _slots_dirty.  A steady pure-decode run
        re-stages nothing."""
        if self.kv.version != self._kv_synced:
            # the mixed step's virtual trash row (row S, all pages
            # unmapped -> physical page 0) rides permanently at the end
            tbl = np.concatenate(
                [self.kv.table,
                 np.zeros((1, self.kv.pages_per_slot), np.int32)], axis=0)
            self._d_table = self._stage(tbl)
            self._kv_synced = self.kv.version
        if self._slots_dirty:
            S = len(self.slots)
            pos = np.zeros(S, np.int32)
            toks = np.zeros(S, np.int32)
            gen = np.zeros(S, np.int32)
            keys = np.zeros((S, self._kk, 2), np.uint32)
            temp = np.zeros(S, np.float32)
            topk = np.zeros(S, np.int32)
            topp = np.zeros(S, np.float32)
            eos = np.full(S, -1, np.int32)
            maxnew = np.zeros(S, np.int32)
            for s, sl in enumerate(self.slots):
                if sl is None:
                    continue
                pos[s], toks[s], gen[s] = sl.pos, sl.last_tok, sl.gen
                keys[s, :sl.keys.shape[0]] = sl.keys
                temp[s] = sl.req.temperature
                topk[s] = sl.req.top_k
                topp[s] = sl.req.top_p
                eos[s] = sl.req.eos_id
                maxnew[s] = sl.req.max_new
            self._d_pos = self._stage(pos)
            self._d_toks = self._stage(toks)
            self._d_gen = self._stage(gen)
            self._d_keys = self._stage(keys)
            self._d_temp = self._stage(temp)
            self._d_topk = self._stage(topk)
            self._d_topp = self._stage(topp)
            # the scanned step's on-device retirement operands: eos id and
            # max_new per slot — same lifecycle cadence as the knobs above
            self._d_eos = self._stage(eos)
            self._d_maxnew = self._stage(maxnew)
            self._slots_dirty = False

    def _sync_run_mask(self, runnable) -> None:
        """The step's advance mask, device-cached: re-uploaded only when
        which slots advance actually changes (a pause, an admission, a
        retire) — constant across a steady decode run."""
        mask = np.zeros(len(self.slots), bool)
        mask[list(runnable)] = True
        if self._run_host is None or not np.array_equal(mask,
                                                        self._run_host):
            self._run_host = mask
            self._d_run = self._stage(mask)

    def _build_state(self) -> EngineState:
        """Assemble the step's state pytree from the current device
        components — pure host-side tuple construction, no transfers
        (pools enter via kv.pools so admission-time pack/COW rebinds are
        picked up automatically)."""
        return EngineState(pools=self.kv.pools, table=self._d_table,
                           pos=self._d_pos, toks=self._d_toks,
                           gen=self._d_gen, keys=self._d_keys,
                           temp=self._d_temp, topk=self._d_topk,
                           topp=self._d_topp)

    def _unpack_state(self, st: EngineState) -> None:
        """Rebind every component from a step's (donated-buffer) output —
        the old arrays were just consumed, no stale aliases may survive."""
        self.kv.pools = st.pools
        self._d_table = st.table
        self._d_pos = st.pos
        self._d_toks = st.toks
        self._d_gen = st.gen
        self._d_keys = st.keys
        self._d_temp = st.temp
        self._d_topk = st.topk
        self._d_topp = st.topp

    # -- lifecycle tracing helpers ----------------------------------------
    def _tr_on(self) -> bool:
        t = self.tracer
        return t is not None and t.enabled

    def _trace_attrs(self, req_id, attrs: dict) -> dict:
        """Merge the request's inbound trace context (trace_id + the
        sender's span id) into span attrs — the cross-process stitch."""
        tc = self._req_trace.get(req_id)
        if tc:
            attrs = dict(attrs)
            attrs.setdefault("trace_id", tc.get("trace_id"))
            if tc.get("parent"):
                attrs.setdefault("parent", tc["parent"])
        return attrs

    def _tr_begin(self, req_id, phase: str, **attrs) -> None:
        """Open the request's next lifecycle phase (queued / prefill /
        decode / replay).  At most one phase is open per request; the
        previous one must have been closed by _tr_end.  The phase clock
        runs UNCONDITIONALLY (per-request latency attribution is always
        on); the span records only while the tracer is enabled."""
        now = time.perf_counter()
        self._req_phase[req_id] = (phase, now)
        if self._tr_on():
            self._obs_open[req_id] = [
                phase, f"req:{req_id}", now,
                self._trace_attrs(req_id, attrs) or None]

    def _tr_end(self, req_id, **attrs) -> None:
        now = time.perf_counter()
        ph = self._req_phase.pop(req_id, None)
        if ph is not None:
            a = self._req_attr.setdefault(req_id, {})
            a[ph[0]] = a.get(ph[0], 0.0) + (now - ph[1])
        h = self._obs_open.pop(req_id, None)
        if h is not None:
            name, track, t0, sattrs = h
            if attrs:
                sattrs = dict(sattrs or (), **attrs)
            self.tracer.add(name, t0, now - t0, track=track, attrs=sattrs)

    def _tr_instant(self, req_id, name: str, **attrs) -> None:
        if self._tr_on():
            self.tracer.instant(name, track=f"req:{req_id}",
                                **self._trace_attrs(req_id, attrs))

    def _bump_attr(self, req_id, key: str, by: int = 1) -> None:
        """Occurrence counter feeding the timing breakdown (preempts,
        prefill chunks, spec drafted/accepted)."""
        a = self._req_attr.setdefault(req_id, {})
        a[key] = a.get(key, 0) + by

    def _finish_timing(self, req_id) -> dict:
        """Fold the request's phase accumulators into the `timing`
        breakdown the done frame carries: per-phase wall ms + occurrence
        counts.  The phases are contiguous (each _tr_end is immediately
        followed by the next _tr_begin), so their sum IS the engine-side
        request wall time — `total_ms` restates it for SLO debugging
        without a trace viewer."""
        self._req_phase.pop(req_id, None)     # closed by the final _tr_end
        a = self._req_attr.pop(req_id, {})
        ms = {k: round(a.get(p, 0.0) * 1e3, 3) for k, p in
              (("queue_ms", "queued"), ("prefill_ms", "prefill"),
               ("decode_ms", "decode"), ("replay_ms", "replay"))}
        ms["total_ms"] = round(sum(ms.values()), 3)
        for k, src in (("prefill_chunks", "chunks"),
                       ("preempts", "preempts"),
                       ("spec_drafted", "spec_drafted"),
                       ("spec_accepted", "spec_accepted")):
            if a.get(src):
                ms[k] = int(a[src])
        return ms

    # -- public API -------------------------------------------------------
    def validate(self, req: Request) -> None:
        """Raise ValueError if `req` can never be served by this engine's
        capacity — pure read of construction-time constants, so a front
        end on another thread can reject before enqueueing."""
        if req.max_new < 0:
            # jax.random.split(rng, -1) inside _admit would kill the pump
            raise ValueError(
                f"request {req.req_id!r}: max_new {req.max_new} is negative")
        if req.max_new == 0:
            return
        p = req.prompt_ids.size
        cap = self.kv.capacity_tokens
        if p + req.max_new > cap:
            raise ValueError(
                f"request {req.req_id!r}: prompt {p} + max_new "
                f"{req.max_new} exceeds the {cap}-token slot capacity "
                f"(pages_per_slot * page_size) — raise max_context")
        # guaranteed-completion bound: the last decode step writes KV at
        # position p + max_new - 2, so a request that never hits eos needs
        # pages covering p + max_new - 1 tokens.  A pool below that can
        # only preempt-and-replay the request forever once it is alone.
        need = self.kv.pages_for(max(p + req.max_new - 1, p))
        if need > self.kv.num_pages - 1:
            raise ValueError(
                f"request {req.req_id!r} needs up to {need} pages to "
                f"complete but the pool holds {self.kv.num_pages - 1} — "
                f"raise num_pages")

    def add_request(self, req: Request) -> None:
        """Enqueue; admission happens inside step()/run()."""
        self.validate(req)
        if req.trace:
            self._req_trace[req.req_id] = req.trace
        if req.max_new == 0:
            # lm_generate(max_new=0) returns the prompt unchanged whatever
            # its length — resolve before any capacity/page validation,
            # since this request never touches a slot or a page
            self._finish(req.req_id, req.prompt_ids.copy(), "length")
            return
        self._tr_begin(req.req_id, "queued",
                       prompt_len=int(req.prompt_ids.size),
                       max_new=req.max_new)
        self.flight.record("queued", req=str(req.req_id),
                           prompt_len=int(req.prompt_ids.size),
                           max_new=req.max_new)
        self.queue.append(req)

    def cancel(self, request_id, reason: str = "cancelled") -> bool:
        """Abort a queued or in-flight request: its slot and pages return
        to the pool THIS call (reusable by waiting requests on the very
        next step), its tokens-so-far land in results with the given
        finish reason.  False when the id is unknown or already finished.
        Call from the step()-driving thread only (the scheduler state is
        not locked)."""
        for i, r in enumerate(self.queue):
            if r.req_id == request_id:
                del self.queue[i]
                self._count_abort(reason)
                stash = r._preempted_gen or []
                if stash:
                    # the preempt rollback un-banked these on the promise
                    # the restart would re-emit them; an abort breaks that
                    # promise, and they WERE genuinely emitted (and
                    # possibly streamed) — restore the count
                    self.tokens_generated += len(stash)
                toks = np.concatenate(
                    [r.prompt_ids,
                     np.asarray(stash, np.int32)]).astype(np.int32)
                self._finish(request_id, toks, reason)
                return True
        for s, sl in enumerate(self.slots):
            if sl is not None and sl.req.req_id == request_id:
                gen = sl.generated
                stash = sl.req._preempted_gen or []
                if len(stash) > len(gen):
                    # cancelled MID-REPLAY after a preemption: the replay
                    # has not yet caught up to what was already emitted
                    # (and streamed) before the preempt.  Determinism
                    # makes both identical prefixes of one stream — report
                    # the longer one and restore the still-un-rebanked
                    # remainder of the preempt rollback
                    self.tokens_generated += len(stash) - len(gen)
                    gen = stash
                toks = np.concatenate(
                    [sl.req.prompt_ids,
                     np.asarray(gen, np.int32)]).astype(np.int32)
                self._donate(s)
                self.kv.release(s)
                self.slots[s] = None
                self._slots_dirty = True
                self._count_abort(reason)
                self._finish(request_id, toks, reason)
                return True
        return False

    def _count_abort(self, reason: str) -> None:
        if reason == "deadline":
            self.n_expired += 1
        else:
            self.n_cancelled += 1

    def _sweep_deadlines(self) -> None:
        """Expire every queued/in-flight request whose deadline passed on
        the engine clock — runs at the top of step(), BEFORE admission, so
        an expired queued request never takes a slot and an expired slot's
        pages free up for this very step's admissions."""
        now = self.clock()
        expired = [r.req_id for r in self.queue
                   if r.deadline is not None and r.deadline <= now]
        expired += [sl.req.req_id for sl in self.slots
                    if sl is not None and sl.req.deadline is not None
                    and sl.req.deadline <= now]
        for rid in expired:
            self.cancel(rid, reason="deadline")

    def step(self) -> bool:
        """One scheduler iteration: sweep deadlines -> admit -> one
        compiled step over all slots -> retire.  Returns False when idle
        (nothing in flight and nothing admittable).

        With chunked prefill on, a step with any slot mid-prefill runs
        the MIXED step: decode rows and prompt-chunk rows pack into one
        ragged [max_step_tokens] dispatch under the token budget.  Steps
        with only decoding slots keep the classic [S, 1] decode step —
        the steady state pays nothing for the chunk machinery."""
        self._sweep_deadlines()
        self._admit_from_queue()
        live = [s for s in range(len(self.slots)) if self.slots[s] is not None]
        if not live:
            self._t_prev_decode = None   # idle: don't charge the idle gap
            return False
        while True:
            # decode-mode slots need their next page; prefill-mode slots
            # (gen == 0, chunked admission) had their prompt pages secured
            # at reservation and can always take chunk rows
            decoding = [s for s in live if self.slots[s].gen > 0]
            filling = [s for s in live if self.slots[s].gen == 0]
            runnable = [s for s in decoding
                        if self.kv.try_grow(s, self.slots[s].pos + 1)]
            if runnable or (filling and not decoding):
                # chunk-only steps are progress ONLY while nothing is
                # decoding: if every decoding slot is page-starved, letting
                # a filler keep chunking would stall their inter-token
                # latency for its whole remaining prefill — the exact
                # HOL blocking the budget exists to bound — and the wedge
                # preemption below would then evict the filler anyway,
                # discarding a just-finished prefill
                break
            # overcommitted-pool wedge: every decoding slot needs its next
            # page and the free list is dry (eviction included).  Preempt
            # the YOUNGEST live slot (the recompute policy of
            # arXiv:2605.25645-style engines) — usually the mid-prefill
            # filler holding the reserved pages: release its pages and
            # requeue its request at the queue front.  A decode victim's
            # deterministic per-request key schedule regenerates the exact
            # same tokens on re-admission; a mid-prefill victim donates its
            # committed chunk pages and prefix-hits them on replay — either
            # way preemption is invisible in the output (and in the parity
            # oracle).
            victim = max(live, key=lambda s: self.slots[s].admit_seq)
            self._preempt(victim)
            live.remove(victim)
            if not live:
                return True        # pages freed; next step() re-admits
        if self.spec_k > 0:
            # speculative mode: the drafter proposes per decoding slot
            # (dynamic k may choose 0 for cold/low-accept slots); any
            # drafts (or chunk rows) route through the verify step — a
            # zero-draft pure-decode step keeps the cheap [S, 1] or
            # scanned signature, so an unhelpful drafter costs nothing
            # steady-state beyond the draft pass itself
            drafts = self._propose_drafts(runnable)
            if drafts or filling:
                return self._run_spec_step(live, runnable, filling,
                                           drafts)
        elif filling:
            # mixed prefill/decode load drops to the mixed step PER
            # FLUSH WINDOW — a mid-flight admission is never stalled
            # behind a k-step scan (the scan gate below is only ever
            # reached with no prefill in flight)
            return self._run_mixed_step(live, runnable, filling)

        if self.decode_steps > 1 \
                and (self.spec_k == 0 or self.decode_mode == "auto") \
                and self._scan_window_ok(runnable, self.decode_steps):
            # pure-decode steady state with multi-step on: ONE scanned
            # dispatch advances every runnable slot up to k tokens.  Any
            # slot that cannot secure pages for its whole window drops
            # THIS dispatch back to the k=1 step below (progress without
            # livelock); mixed/spec steps never scan — the engine returns
            # to the scanned path once it is pure-decode again.  Under
            # decode_mode="auto" this is how speculation and multi-step
            # COMPOSE: the drafter already had its say at this boundary
            # (above) and proposed nothing, so the window is draft-free
            # and the scan is the best remaining dispatch; "static"
            # keeps the legacy spec_k > 0 exclusion.
            return self._run_scan_step(live, runnable, self.decode_steps)

        traced = self._tr_on()
        t_step = time.perf_counter() if traced else 0.0
        S = len(self.slots)
        for s in runnable:
            sl = self.slots[s]
            # a shared page is never written: the page receiving this
            # step's K/V write must be private to the slot (admission's
            # COW guarantees it — this tripwire catches refcount bugs
            # before they corrupt a cached prefix)
            assert self.kv.page_writable(
                int(self.kv.table[s, sl.pos // self.kv.page_size])), \
                f"slot {s} would write a shared page"
        # per-slot pos/toks/gen/keys/knobs already live on device; a
        # steady decode run enters the compiled step with ZERO host
        # staging (sync uploads only what admissions/retires/pauses
        # actually changed).  The state buffers are donated — rebind
        # every component so no stale (deleted-buffer) aliases survive.
        self._sync_run_mask(runnable)
        self._sync_device_state()
        st, nxt = self._decode_step(self.params, self._build_state(),
                                    self._d_run)
        self._unpack_state(st)
        self.n_decode_steps += 1
        self.occupancy_sum += len(live) / S
        nxt = np.asarray(nxt)                          # host sync
        self._note_step_metrics(len(runnable), decoded=True)
        if traced:
            # one engine-lane span per compiled step (dispatch + the host
            # token read = the inter-token latency every live slot paid)
            self.tracer.add("decode_step", t_step,
                            time.perf_counter() - t_step, track="engine",
                            attrs={"live": len(live),
                                   "step": self.n_decode_steps})
        for s in runnable:
            self._bank_token(s, int(nxt[s]))
        return True

    def _bank_token(self, s: int, tok: int) -> None:
        """Record one decoded token for slot `s` (shared by the pure
        decode step and the mixed step's decode rows): replay-phase flip,
        stream hook, eos/max_new retirement."""
        sl = self.slots[s]
        if sl.replay_until and sl.gen >= sl.replay_until:
            # the next token is the first FRESH one after a preempt
            # replay — flip the lifecycle phase
            sl.replay_until = 0
            self._tr_end(sl.req.req_id)
            self._tr_begin(sl.req.req_id, "decode")
        sl.generated.append(tok)
        sl.pos += 1
        sl.gen += 1
        sl.last_tok = tok
        self.tokens_generated += 1
        if self.on_token is not None:
            self.on_token(sl.req.req_id, tok, sl.gen - 1)
        if tok == sl.req.eos_id or sl.gen >= sl.req.max_new:
            self._retire(s)

    def _note_step_metrics(self, n_tokens: int, decoded: bool) -> None:
        """Token-budget observability: scheduled rows this step, and the
        pump-step gap decoding slots saw (time between consecutive steps
        that advanced at least one decode row — the inter-token latency
        floor HOL-blocking prefill used to blow up)."""
        self.step_tokens_hist.observe(float(n_tokens))
        if decoded:
            now = time.perf_counter()
            if self._t_prev_decode is not None:
                self.decode_gap_hist.observe(
                    (now - self._t_prev_decode) * 1e3)
            self._t_prev_decode = now

    def _scan_window_ok(self, runnable, k: int) -> bool:
        """Page precondition for ONE k-step scanned dispatch: every
        runnable slot must hold pages for its whole window — min(k,
        tokens it can still emit) positions from pos (a slot that hits
        eos earlier simply stops writing; a retired slot's one frozen
        recompute lands at most one position past its last token, still
        inside the window).  Any shortfall reports False and the caller
        falls back to the k=1 step for this dispatch — the +1 page every
        runnable slot already secured guarantees progress, and the next
        boundary retries after retires/eviction free pages."""
        ok = True
        for s in runnable:
            sl = self.slots[s]
            need = min(k, sl.req.max_new - sl.gen)
            if not self.kv.try_grow(s, sl.pos + need):
                ok = False
        return ok

    def _run_scan_step(self, live, runnable, k: int) -> bool:
        """ONE scanned dispatch: k identical decode bodies advance every
        runnable slot on device (pos/gen/toks/KV writes all inside the
        scan), the host unpacking a [k, S] token block at the boundary.
        Per-slot banking cuts each slot's column at its own eos/max_new —
        the exact retirement the device run mask applied — so host
        mirrors re-converge with device state without any readback."""
        traced = self._tr_on()
        t_step = time.perf_counter() if traced else 0.0
        S = len(self.slots)
        psize = self.kv.page_size
        for s in runnable:
            sl = self.slots[s]
            # every page the window can touch must be private (the k=1
            # tripwire, widened to the window span)
            last = sl.pos + min(k, sl.req.max_new - sl.gen) - 1
            for j in range(sl.pos // psize, last // psize + 1):
                assert self.kv.page_writable(int(self.kv.table[s, j])), \
                    f"slot {s} scan window would write a shared page"
        self._sync_run_mask(runnable)
        self._sync_device_state()
        st, blk = self._scan_step_fn()(
            k, self.params, self._build_state(), self._d_run,
            self._d_eos, self._d_maxnew)
        self._unpack_state(st)
        self.n_decode_steps += 1
        self.n_scan_flushes += 1
        self.n_scan_steps += k
        self.occupancy_sum += len(live) / S
        blk = np.asarray(blk)                          # [k, S] host sync
        self._note_step_metrics(len(runnable), decoded=True)
        if traced:
            self.tracer.add("scan_step", t_step,
                            time.perf_counter() - t_step, track="engine",
                            attrs={"live": len(live), "k": k,
                                   "step": self.n_decode_steps})
        # per-flush, never per-token: one boundary event each k tokens
        self.flight.record("scan_flush", k=k, slots=len(runnable))
        for s in runnable:
            sl = self.slots[s]
            burst = []
            for i in range(k):
                t = int(blk[i, s])
                burst.append(t)
                if t == sl.req.eos_id or sl.gen + len(burst) >= \
                        sl.req.max_new:
                    break                # device run mask froze here too
            self.cur_burst = len(burst)
            try:
                for t in burst:
                    self._bank_token(s, t)
            finally:
                self.cur_burst = 1
        return True

    def _run_mixed_step(self, live, runnable, filling) -> bool:
        """ONE mixed prefill/decode dispatch: pack each runnable decode
        slot's single row plus up to `prefill_chunk` prompt rows per
        mid-prefill slot into a flat [max_step_tokens] ragged row list
        (padding rows aim at a virtual all-trash table row), run the
        compiled mixed step, then bank decode tokens and advance chunk
        cursors.  A slot whose FINAL chunk ran this step emits token 0
        from the last prompt position's logits (keys[0] — the same key
        schedule the legacy one-dispatch prefill consumed), so chunk
        rows emit nothing until their final chunk.

        The per-step token budget is the HOL-blocking bound: decode rows
        are packed FIRST (every decoding slot advances every step it has
        pages for), chunk rows only fill what remains — so no single
        step, whatever the prompt mix, exceeds max_step_tokens rows."""
        traced = self._tr_on()
        t_step = time.perf_counter() if traced else 0.0
        S = len(self.slots)
        T = self.max_step_tokens
        ps = self.kv.page_size
        row_ids = np.zeros(T, np.int32)
        row_slot = np.full(T, S, np.int32)   # S = the virtual trash row
        row_pos = np.zeros(T, np.int32)
        sample_row = np.zeros(S, np.int32)
        # device-state advance masks: adv[s] = tokens slot s commits this
        # step (1 per decode row, chunk length per chunk run), emit[s] =
        # slot s banks a sampled token (decode rows + final chunks).  The
        # compiled step advances pos/gen/toks from these; keys and knobs
        # already live in the EngineState (keys[s, gen[s]] — gen 0 at a
        # final chunk IS the legacy keys[0] decision).
        adv = np.zeros(S, np.int32)
        emit = np.zeros(S, bool)
        r = 0
        for s in runnable:
            sl = self.slots[s]
            # same shared-page write tripwire as the pure decode step
            assert self.kv.page_writable(
                int(self.kv.table[s, sl.pos // ps])), \
                f"slot {s} would write a shared page"
            row_ids[r] = sl.last_tok
            row_slot[r] = s
            row_pos[r] = sl.pos
            sample_row[s] = r
            adv[s] = 1
            emit[s] = True
            r += 1
        advanced, r = self._pack_chunk_rows(
            filling, row_ids, row_slot, row_pos, sample_row, adv, emit,
            r, T - r)
        # the state table already carries the virtual trash row (row S) —
        # padding rows gather/scatter only page 0.  Row packing is this
        # step's scheduling decision, so the six row/mask operands stage
        # per mixed step; the EngineState (donated, rebound) does not.
        self._sync_device_state()
        st, nxt = self._mixed_step(
            self.params, self._build_state(), self._stage(row_ids),
            self._stage(row_slot), self._stage(row_pos),
            self._stage(sample_row), self._stage(adv), self._stage(emit))
        self._unpack_state(st)
        self.n_decode_steps += 1
        self.n_mixed_steps += 1
        self.occupancy_sum += len(live) / S
        nxt = np.asarray(nxt)                          # host sync
        self._note_step_metrics(r, decoded=bool(runnable))
        if traced:
            self.tracer.add("decode_step", t_step,
                            time.perf_counter() - t_step, track="engine",
                            attrs={"live": len(live),
                                   "step": self.n_decode_steps,
                                   "mixed": True, "rows": r,
                                   "decode_rows": len(runnable)})
        for s in runnable:
            self._bank_token(s, int(nxt[s]))
        self._advance_chunks(advanced, lambda s: int(nxt[s]))
        return True

    def _pack_chunk_rows(self, filling, row_ids, row_slot, row_pos,
                         sample_row, adv, emit, r: int, budget: int):
        """Pack up to `prefill_chunk` prompt rows per mid-prefill slot
        (admit order) into the ragged row list, starting at row `r`,
        never exceeding `budget` rows — the chunk-scheduling half SHARED
        by the mixed and speculative verify steps, so the final-chunk
        emission rule, the shared-page tripwire, and the chunk_sched
        accounting can never diverge between them.  A slot whose FINAL
        chunk lands this step gets its sampling row pointed at the last
        prompt position (`sample_row[s]`; the verify step's chain
        position 0) and `emit[s]` set — token 0 sampled with keys[gen=0],
        the legacy prefill decision.  Returns (advanced, r')."""
        ps = self.kv.page_size
        advanced = []                        # (slot, n_rows, final)
        for s in sorted(filling, key=lambda s: self.slots[s].admit_seq):
            if budget <= 0:
                break
            sl = self.slots[s]
            p = sl.req.prompt_ids.size
            n = self._chunk_rows_for(s, budget)
            # every page this chunk writes must be private to the slot
            # (reservation COW'd the shared boundary page; mapped prefix
            # pages below the cursor are never written)
            for j in range(sl.pos // ps, (sl.pos + n - 1) // ps + 1):
                assert self.kv.page_writable(int(self.kv.table[s, j])), \
                    f"slot {s} chunk would write shared page " \
                    f"{int(self.kv.table[s, j])}"
            row_ids[r:r + n] = sl.req.prompt_ids[sl.pos:sl.pos + n]
            row_slot[r:r + n] = s
            row_pos[r:r + n] = np.arange(sl.pos, sl.pos + n)
            final = sl.pos + n == p
            adv[s] = n
            if final:
                sample_row[s] = r + n - 1
                emit[s] = True
            self.n_prefill_chunks += 1
            self._bump_attr(sl.req.req_id, "chunks")
            self.flight.record("chunk_sched", req=str(sl.req.req_id),
                               slot=s, start=int(sl.pos), tokens=int(n),
                               final=final)
            advanced.append((s, n, final))
            budget -= n
            r += n
        return advanced, r

    def _chunk_rows_for(self, s: int, budget: int) -> int:
        """Rows slot `s`'s next prefill chunk takes under `budget` — the
        ONE scheduling formula, shared by _pack_chunk_rows and the
        verify step's chunk-reserve computation so the reserve can never
        under-count what the packing will actually schedule."""
        sl = self.slots[s]
        return min(sl.req.prompt_ids.size - sl.pos, self.prefill_chunk,
                   budget)

    def _advance_chunks(self, advanced, tok0_of) -> None:
        """Post-step chunk bookkeeping shared by the mixed and verify
        steps: advance each chunked slot's cursor, and emit token 0
        (`tok0_of(s)` — that slot's sampled row) for final chunks."""
        for s, n, final in advanced:
            sl = self.slots[s]
            sl.pos += n
            if final:
                self._emit_first(s, tok0_of(s))

    # -- speculative decoding (docs/serving.md "Speculative decoding") ----
    def _dyn_k(self, sl) -> int:
        """Per-slot draft depth for this flush window.  Static mode:
        always spec_k.  Dynamic mode (`spec_dynamic=True`): the slot's
        accept-rate EWMA picks k_s ∈ {0..spec_k} — a cold slot pays a
        ONE-row probe (not k wasted verify rows), a low-accept slot
        decays to plain decode (k=0) with a paced k=1 re-probe every
        `_PROBE_EVERY` windows so a workload that turns repetitive can
        climb back, and a high-accept slot rides the full depth.  The
        choice is host-side data (chain packing is ragged by row count),
        so dynamic k adds ZERO verify-step signatures."""
        if not self.spec_dynamic:
            return self.spec_k
        if sl.accept_ewma is None:
            return min(1, self.spec_k)           # cold: cheapest probe
        k = int(round(sl.accept_ewma * self.spec_k))
        if k <= 0:
            sl.probe_tick += 1
            if sl.probe_tick >= _PROBE_EVERY:
                sl.probe_tick = 0
                return 1
            return 0
        return min(k, self.spec_k)

    def _draft_ctx(self, s: int, W: int) -> np.ndarray:
        """Slot `s`'s drafting context: the most recent W tokens of
        prompt + generated, newest last — the drafter's search window's
        tail, so the host cost stays O(window) per slot, not O(context)
        as generation grows."""
        sl = self.slots[s]
        gen_tail = sl.generated[-W:]
        need = W - len(gen_tail)
        if need > 0 and sl.req.prompt_ids.size:
            return np.concatenate(
                [sl.req.prompt_ids[-need:],
                 np.asarray(gen_tail, np.int32)])
        return np.asarray(gen_tail, np.int32)

    def _propose_drafts(self, runnable) -> dict:
        """Ask the drafter for lookahead tokens per decoding slot (host
        side, between steps — the scan/flush boundary).  The per-slot
        cap is exact-by-construction: a chain emits at most k+1 tokens,
        so k never exceeds the tokens the request may still emit
        (max_new - gen - 1), and the deepest draft write (pos + k) never
        exceeds slot capacity — the same `p + max_new - 2` bound
        validate() already guarantees pages for.  Empty proposals drop
        out entirely (their slot rides the plain decode row or the
        scanned window).

        Drafters exposing `propose_batch` (ModelDrafter) get ALL slots'
        windowed contexts in ONE call — one jitted [S, W] -> [S, spec_k]
        dispatch at site `serving.draft_step`, ALWAYS at depth spec_k so
        dynamic per-slot k (applied by host-side slicing) never mints a
        new signature.  Per-slot `propose` drafters own the clamp
        contract (<= k tokens, nothing past eos) — the tripwire below
        fails loudly instead of silently truncating, so a drafter bug
        can no longer masquerade as a low accept rate."""
        out = {}
        if not runnable or self.spec_k <= 0:
            return out
        cap = self.kv.capacity_tokens
        W = int(getattr(self.drafter, "window", 0)) or cap
        want = {}
        for s in runnable:
            sl = self.slots[s]
            k = min(self._dyn_k(sl), sl.req.max_new - sl.gen - 1,
                    cap - 1 - sl.pos)
            self.spec_k_hist.observe(float(max(k, 0)))
            if k > 0:
                want[s] = k
        if not want:
            return out
        traced = self._tr_on()
        t0 = time.perf_counter()
        if hasattr(self.drafter, "propose_batch"):
            out = self._propose_batched(want, W)
        else:
            for s, k in want.items():
                sl = self.slots[s]
                ctx = self._draft_ctx(s, W)
                if self._drafter_takes_eos:
                    d = self.drafter.propose(ctx, k,
                                             eos_id=sl.req.eos_id)
                else:
                    d = self.drafter.propose(ctx, k)
                d = np.asarray(d, np.int32).reshape(-1)
                assert d.size <= k, \
                    f"drafter returned {d.size} tokens for k={k} — the " \
                    f"clamp contract is the drafter's (see " \
                    f"serving/drafter.py); truncating here would skew " \
                    f"accept-rate stats"
                if d.size:
                    out[s] = d
        dt = time.perf_counter() - t0
        self.draft_ms_hist.observe(dt * 1e3)
        if out:
            self.n_draft_steps += 1
            self.flight.record("draft_step", slots=len(out),
                               drafter=self.drafter_kind,
                               ms=round(dt * 1e3, 3))
            if traced:
                self.tracer.add("draft_step", t0, dt, track="engine",
                                attrs={"slots": len(out),
                                       "k": self.spec_k,
                                       "drafter": self.drafter_kind})
        return out

    def _propose_batched(self, want: dict, W: int) -> dict:
        """ONE batched draft dispatch for every drafting slot: assemble
        the [S, W] windowed-context matrix (idle rows ride as length-1
        zero rows — S is the engine's slot count, fixed, so the
        draft-step signature is stable), call `propose_batch` at depth
        spec_k, then slice each slot's row to ITS dynamic k and cut at
        the -1 padding the drafter's eos clamp left."""
        S = len(self.slots)
        ctx = np.zeros((S, W), np.int32)
        lens = np.ones(S, np.int32)
        eos = np.full(S, -1, np.int32)
        for s in want:
            c = self._draft_ctx(s, W)
            ctx[s, :c.size] = c[-W:]
            lens[s] = max(int(c.size), 1)
            eos[s] = int(self.slots[s].req.eos_id)
        props = np.asarray(self.drafter.propose_batch(
            ctx, lens, self.spec_k, eos_ids=eos))
        out = {}
        for s, k in want.items():
            row = np.asarray(props[s, :k], np.int32).reshape(-1)
            stop = np.flatnonzero(row < 0)       # -1 = post-eos padding
            if stop.size:
                row = row[:int(stop[0])]
            if row.size:
                out[s] = row
        return out

    def _run_spec_step(self, live, runnable, filling, drafts) -> bool:
        """ONE speculative verify dispatch: every decoding slot packs a
        CHAIN of consecutive rows — its regular next-token row at `pos`
        plus up to k draft rows at pos+1..pos+k — and mid-prefill slots'
        chunk rows share the same dispatch (mode-aware packing).  Budget
        priority: decode base rows first (every decoder advances), then
        the chunk rows' RESERVE (exactly what the mixed step would have
        scheduled — drafting can never starve a prompt's first token),
        and drafts spend only what is left.  The
        ragged attention core scatters ALL rows' K/V before reading, so
        draft row i attends the committed context plus drafts 1..i-1
        under the causal mask — precisely the context the sequential
        engine would have if those drafts were the true tokens.

        Acceptance is computed ON DEVICE (no host round trip inside the
        step): every chain position samples with the slot's own key for
        that generation index, the accepted length is the leading run of
        draft agreement, and pos/gen/last-token advance by accepted+1.
        The host then banks the emitted tokens through the ordinary
        `_bank_token` path (eos/max_new semantics unchanged — a chain
        truncates at eos exactly where the sequential stream would) and
        rolls back the page tail the rejection left unjustified
        (`kv.uncommit_tail` — the allocator's preempt-grade rollback).

        Chains need page cover for their deepest write; a page-starved
        slot verifies fewer drafts instead of stalling (the plain row
        needs only the page the runnable check already secured)."""
        traced = self._tr_on()
        t_step = time.perf_counter() if traced else 0.0
        S = len(self.slots)
        K = self.spec_k
        T = self.max_step_tokens if self.prefill_chunk is not None \
            else S * (K + 1)
        ps = self.kv.page_size
        row_ids = np.zeros(T, np.int32)
        row_slot = np.full(T, S, np.int32)   # S = the virtual trash row
        row_pos = np.zeros(T, np.int32)
        first_row = np.zeros(S, np.int32)
        n_draft = np.zeros(S, np.int32)
        draft_toks = np.zeros((S, K), np.int32)
        spec = np.zeros(S, bool)
        emit = np.zeros(S, bool)
        adv_chunk = np.zeros(S, np.int32)
        r = 0
        # every decoding slot's base row is reserved BEFORE any draft or
        # chunk row spends budget — decoders advance every step whatever
        # the speculation does (the mixed step's HOL discipline)
        budget = T - len(runnable)
        assert budget >= 0, \
            "token budget below the decoding slot count (set_chunking " \
            "guarantees max_step_tokens > num_slots)"
        # ...and the chunk rows' share is reserved BEFORE any draft row:
        # speculation spends only what prefill leaves over, so drafting
        # decoders can never starve a mid-prefill prompt's chunks — the
        # first-token HOL bound chunked prefill exists for.  The reserve
        # is exactly what the mixed step would have scheduled them.
        chunk_reserve = 0
        if filling:
            left = budget
            for s in sorted(filling,
                            key=lambda s: self.slots[s].admit_seq):
                if left <= 0:
                    break
                n = self._chunk_rows_for(s, left)
                chunk_reserve += n
                left -= n
        budget -= chunk_reserve
        for s in runnable:
            sl = self.slots[s]
            d = drafts.get(s)
            nd = 0 if d is None else min(int(d.size), budget)
            if nd > 0 and not self.kv.try_grow(s, sl.pos + nd + 1,
                                               evict=False):
                # page-starved chain: verify only what the slot's pages
                # cover (pages already grabbed stay with the slot — the
                # post-step uncommit returns whatever acceptance cannot
                # justify, so a dry pool shrinks ambition, never
                # wedges).  evict=False: optimistic draft pages must
                # never cost a committed cached prefix its retention —
                # a rejection would hand them back this very step
                nd = min(nd, max(0, int(self.kv._n_pages[s]) * ps
                                 - sl.pos - 1))
            for j in range(sl.pos // ps, (sl.pos + nd) // ps + 1):
                # the chain's whole write span must be private pages
                # (the decode tripwire, stretched over the draft tail)
                assert self.kv.page_writable(int(self.kv.table[s, j])), \
                    f"slot {s} chain would write shared page " \
                    f"{int(self.kv.table[s, j])}"
            row_ids[r] = sl.last_tok
            row_slot[r] = s
            row_pos[r] = sl.pos
            first_row[s] = r
            spec[s] = True
            emit[s] = True
            r += 1
            if nd > 0:
                row_ids[r:r + nd] = d[:nd]
                row_slot[r:r + nd] = s
                row_pos[r:r + nd] = np.arange(sl.pos + 1,
                                              sl.pos + 1 + nd)
                draft_toks[s, :nd] = d[:nd]
                n_draft[s] = nd
                self.n_spec_drafted += nd
                self.flight.record("spec_propose",
                                   req=str(sl.req.req_id), slot=s,
                                   k=int(nd), pos=int(sl.pos))
                budget -= nd
                r += nd
        # chunk rows take their reserve plus whatever the drafts left
        # unspent (T - r is exactly that); a final chunk's chain
        # position 0 is its last prompt row, sampled with keys[gen=0]
        advanced, r = self._pack_chunk_rows(
            filling, row_ids, row_slot, row_pos, first_row, adv_chunk,
            emit, r, T - r)
        self._sync_device_state()
        st, sampled, acc = self._spec_step(
            self.params, self._build_state(), self._stage(row_ids),
            self._stage(row_slot), self._stage(row_pos),
            self._stage(first_row), self._stage(n_draft),
            self._stage(draft_toks), self._stage(spec),
            self._stage(emit), self._stage(adv_chunk))
        self._unpack_state(st)
        self.n_decode_steps += 1
        self.n_spec_steps += 1
        if advanced:
            self.n_mixed_steps += 1
        self.occupancy_sum += len(live) / S
        sampled = np.asarray(sampled)                  # host sync
        acc = np.asarray(acc)
        self._note_step_metrics(r, decoded=bool(runnable))
        if traced:
            self.tracer.add("decode_step", t_step,
                            time.perf_counter() - t_step, track="engine",
                            attrs={"live": len(live),
                                   "step": self.n_decode_steps,
                                   "spec": True, "rows": r,
                                   "decode_rows": len(runnable)})
        for s in runnable:
            sl = self.slots[s]
            a = int(acc[s])
            nd = int(n_draft[s])
            self.n_spec_accepted += a
            self.n_spec_chains += 1
            if self.spec_dynamic and nd:
                # feed the slot's accept EWMA BEFORE banking may retire
                # it — the next flush window's _dyn_k steers by this.
                # Draft-free rows (nd == 0) carry no signal: skipped, so
                # a k=0 slot's estimate moves only on its paced probes.
                rate = a / nd
                sl.accept_ewma = rate if sl.accept_ewma is None else \
                    (1.0 - _EWMA_ALPHA) * sl.accept_ewma \
                    + _EWMA_ALPHA * rate
            if nd:
                rid = str(sl.req.req_id)
                self._bump_attr(sl.req.req_id, "spec_drafted", nd)
                if a:
                    self._bump_attr(sl.req.req_id, "spec_accepted", a)
                    self.flight.record("spec_accept", req=rid, slot=s,
                                       accepted=a, drafted=nd)
                if nd > a:
                    self.flight.record("spec_reject", req=rid, slot=s,
                                       rejected=nd - a, drafted=nd)
            # host page rollback BEFORE banking: banking may retire the
            # slot (eos / max_new), and retire releases every mapping —
            # while the slot is live, pages past pages_for(pos + a + 1)
            # hold only rejected-draft garbage
            self.kv.uncommit_tail(s, sl.pos + a + 1)
            for i in range(a + 1):
                self._bank_token(s, int(sampled[s, i]))
                self.n_spec_tokens += 1
                if self.slots[s] is None:     # retired mid-chain (eos)
                    break
        self._advance_chunks(advanced, lambda s: int(sampled[s, 0]))
        return True

    def run(self, requests=()) -> dict:
        """Add `requests`, drive step() to completion, and POP
        {req_id: np.int32 tokens (prompt + generated, eos included)} for
        everything that completed during this call (including requests
        queued before it) — earlier, already-collected runs don't bleed
        in, and a long-lived engine holds no unbounded result archive."""
        done_before = set(self.results)
        for r in requests:
            self.add_request(r)
        while self.step():
            pass
        out = {k: self.results.pop(k) for k in list(self.results)
               if k not in done_before}
        for k in out:
            self.finish_reasons.pop(k, None)
            self.finish_timing.pop(k, None)
        return out

    def bucket_for(self, prompt_len: int) -> int:
        """LEGACY-prefill length for a prompt: the feeder bucket,
        page-aligned, capped at slot capacity — one compiled prefill per
        distinct value.  Only the prefill_chunk=None path uses buckets;
        chunked admission derives chunk count from the prompt length, so
        prompts beyond the largest feeder bucket admit without growing
        the signature set (validate() rejects only pool-capacity
        violations)."""
        ps = self.kv.page_size
        Lb = -(-_bucket_len(int(prompt_len)) // ps) * ps
        return min(Lb, self.kv.capacity_tokens)

    # -- scheduling internals --------------------------------------------
    def _admit_from_queue(self) -> None:
        for s in range(len(self.slots)):
            if not self.queue:
                return
            if self.slots[s] is not None:
                continue
            req = self.queue[0]
            res = self._reserve(s, req)
            if res is None:
                # page-starved: keep FIFO order, retry later (_reserve
                # already rolled the slot back to empty — pages stranded
                # on it would be invisible to a retry on a different slot)
                return
            self.queue.popleft()
            if self.prefill_chunk is not None:
                self._admit_chunked(s, req, *res)
            else:
                self._admit(s, req, *res)

    def _reserve(self, s: int, req: Request):
        """Map any cached prefix into empty slot `s` and allocate the
        remaining pages for the whole prompt.  Returns (matched_tokens,
        matched_pages) on success, None on page starvation (slot rolled
        back to empty).

        The prefix walk caps at prompt_len - 1 tokens: at least one token
        always prefills, because sampling token 0 needs the last prompt
        position's logits.  A partial-run boundary match maps one page the
        request will WRITE into mid-run, so it is copy-on-written here, at
        reservation time — the request's divergent suffix must never touch
        the shared original.  The COW runs AFTER the suffix pages are
        secured: a page-starved reservation then fails at try_grow before
        paying the device copy, instead of repeating copy + n_cow +
        flight event on every retry step while the queue head is stuck.

        If the shared mapping cannot be completed (COW page or suffix
        pages unavailable even after eviction), the whole reservation
        rolls back and admission retries COLD: the just-unmapped prefix
        pages drop to refcount zero, so the cold attempt's page-pressure
        eviction can reclaim them — holding them mapped would starve the
        very admission they were meant to speed up (livelock).

        KV SPILL TIER: when the matched path ends in spilled (HOST) runs,
        _restore_spilled faults them back to device FIRST — fresh pages,
        one batched host->device scatter, promote — and the hit then maps
        exactly like an always-device one.  Every restore failure mode
        (budget-starved allocation, a stale host generation, the matched
        device path lost to the restore's own pressure eviction) rolls
        back completely and falls through to cold admission, which the
        exactness oracles prove produces identical tokens."""
        p = req.prompt_ids.size
        if self.prefix is not None:
            nodes, partial = self.prefix.match_nodes(req.prompt_ids[:p - 1])
            path = list(nodes) + ([partial[0]] if partial is not None
                                  else [])
            host_tail = [nd for nd in path if nd.host_id is not None]
            if host_tail and not self._restore_spilled(req, path, host_tail):
                path, partial = [], None        # rolled back: admit cold
            if path:
                mapped = [nd.page for nd in path]
                self.kv.map_shared(s, mapped)
                C = len(nodes) * self.kv.page_size + \
                    (partial[1] if partial is not None else 0)
                ok = self.kv.try_grow(s, p)
                if ok and partial is not None:
                    cow = self.kv.ensure_writable(s, len(mapped) - 1)
                    ok = cow is not None
                    if cow:
                        self.flight.record("prefix_cow",
                                           req=str(req.req_id),
                                           page=int(mapped[-1]),
                                           matched_in_page=int(partial[1]))
                if ok:
                    if host_tail:
                        self.n_restore_hits += 1
                        # tokens of C served from restored pages: the
                        # device-resident full runs cover the first
                        # dev_full * page_size of the match, the rest
                        # (full HOST runs + a HOST boundary's partial
                        # tokens) came back from the host tier
                        dev_full = sum(1 for nd in nodes
                                       if nd not in host_tail)
                        self.restore_tokens_saved += \
                            C - dev_full * self.kv.page_size
                    return (C, len(mapped))
                self.kv.release(s)
        if self.kv.try_grow(s, p):
            return (0, 0)
        self.kv.release(s)
        return None

    def _restore_spilled(self, req: Request, path, host_tail) -> bool:
        """Fault a matched path's spilled tail back to device: take fresh
        pages (spill inhibited, so the host tier — and these very entries
        — can't churn under the allocation's pressure evictions), one
        batched scatter, re-mark cached, promote the nodes.  False = full
        rollback happened and the caller admits cold.  Page counts here
        ride a bucketed jit at the admission boundary — the decode/mixed/
        spec/scan step signatures never move (the compile-watch oracle)."""
        kv, tree = self.kv, self.prefix
        if not all(kv.host_entry_live(nd.host_id) for nd in host_tail):
            # a dead generation (kv.reset without tree.clear — the
            # checkpoint/restore seam) must never resurrect: drop the
            # zombie subtree from its topmost host node and admit cold
            tree.drop_host_subtree(host_tail[0])
            return False
        dev_nodes = [nd for nd in path if nd.host_id is None]
        tree._spill_inhibit = True
        try:
            pages = kv.take_pages(len(host_tail))
        finally:
            tree._spill_inhibit = False
        if pages is None:
            return False
        # the allocation's own eviction ran over the tree: verify the
        # matched DEVICE prefix survived (LRU makes just-touched nodes
        # the last victims, so this only trips when the pool is so small
        # the reservation is infeasible anyway) and the host entries too
        # (destroying a device ancestor drops its host subtree)
        if any(nd.page <= 0 or nd.host_id is not None
               for nd in dev_nodes) or \
                not all(kv.host_entry_live(nd.host_id)
                        for nd in host_tail):
            kv.untake_pages(pages)
            return False
        kv.restore_pages([nd.host_id for nd in host_tail], pages)
        kv.adopt_restored(pages)
        tree.promote(host_tail, pages)
        self.flight.record("restore", req=str(req.req_id),
                           pages=len(pages),
                           host_pages=kv.host_page_count)
        return True

    # -- cross-replica kv transfer (docs/serving.md "Disaggregated
    # prefill/decode") -----------------------------------------------------
    def export_prefix(self, tokens):
        """Serialize the longest DEVICE-resident whole-page cached prefix
        of `tokens` for a kv_push: returns (covered_tokens, meta, payload)
        or None when nothing is cached.  Pump thread only (walks the
        prefix tree and gathers from the pools between steps)."""
        if self.prefix is None:
            return None
        toks = np.asarray(tokens, np.int32).reshape(-1)
        pages, _ = self.prefix.match(toks)
        if not pages:
            return None
        n_tok = len(pages) * self.kv.page_size
        meta, payload = self.kv.export_pages(pages)
        return toks[:n_tok], meta, payload

    def import_prefix(self, tokens, meta: dict, payload: bytes) -> int:
        """Mount a kv_push blob into the prefix tree: take fresh pages,
        scatter the wire bytes in (one bucketed dispatch — the spill
        tier's restore jit), adopt + insert so the NEXT admission of this
        prompt is a prefix hit instead of a re-prefill.  Raises ValueError
        — with the allocator rolled back exactly (`check()` green) — on
        a malformed blob or page starvation; returns nodes newly added.
        Pump thread only: kv.pools is authoritative between steps, so the
        scatter is exactly as safe as an admission-time spill restore."""
        if self.prefix is None:
            raise ValueError("kv import: prefix cache is disabled")
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = int(meta.get("n_pages", 0))
        ps = self.kv.page_size
        if n <= 0 or toks.size != n * ps:
            raise ValueError(
                f"kv import: {toks.size} tokens do not cover "
                f"{n} pages x {ps}")
        pages = self.kv.take_pages(n)
        if pages is None:
            raise ValueError(
                f"kv import: pool cannot cover {n} fresh pages")
        try:
            self.kv.import_pages(meta, payload, pages)
        except (ValueError, AssertionError):
            # import_pages' freshness preconditions are asserts; the
            # server's pump handler treats both as a clean refusal, so
            # both must roll the taken pages back or they leak
            self.kv.untake_pages(pages)
            raise
        self.kv.adopt_restored(pages)
        added = self.prefix.insert(toks, pages, adopted=True)
        self.n_kv_mounts += 1
        self.kv_pages_mounted += n
        self.flight.record("kv_recv", pages=n, mounted=added)
        return added

    def _admit(self, s: int, req: Request, C: int = 0, n_pp: int = 0) -> None:
        """Prefill the prompt (or, on a prefix hit, ONLY its uncached
        suffix) at a bucket length, pack its K/V into the slot's pages,
        sample token 0 from the prefill logits (keys[0] — the same key
        schedule lm_generate consumes).  `C` = tokens already mapped from
        the prefix index across the slot's first `n_pp` pages.

        A re-admission after preemption keeps req._preempted_gen: until the
        deterministic replay catches up, an abort must still report those
        already-delivered tokens (cancel's mid-replay branch).  A later
        preemption simply overwrites it with the longer prefix."""
        self._tr_end(req.req_id)                       # queued ends here
        p = req.prompt_ids.size
        ps = self.kv.page_size
        keys = np.asarray(jax.random.split(req.rng, req.max_new))
        self._count_prefix(req, C, n_pp, p)
        if C > 0:
            # suffix-only prefill: the transformer runs on tokens [C, p)
            # against a cache seeded from the slot's mapped prefix pages
            # (layers_attn's "cont" continuation path), so prefill compute
            # scales with the UNCACHED suffix only.  The suffix is
            # bucketed like cold prefill; C and the in-page offset ride as
            # dynamic operands.
            suf = p - C
            Lb = min(-(-_bucket_len(suf) // ps) * ps,
                     self.kv.capacity_tokens - C)
            self._tr_begin(req.req_id, "prefill", bucket=Lb,
                           prefix_tokens=C)
            ids = np.zeros((1, Lb), np.int32)
            ids[0, :suf] = req.prompt_ids[C:]
            last, kv_suffix = self._prefix_prefill_fn(n_pp, Lb)(
                self.params, self.kv.pools,
                jnp.asarray(self.kv.table[s, :n_pp].copy()),
                jnp.asarray(ids), jnp.asarray([suf], np.int32),
                jnp.asarray([C], np.int32))
            tok0 = int(np.asarray(pick_next(
                last, jnp.asarray(keys[0]),
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, is_probs=self._probs))[0])
            # suffix K/V scatter from in-page offset C % ps across the
            # slot's remaining pages (trash page 0 beyond the prompt)
            n_span = Lb // ps + 1
            pages = np.zeros(n_span, np.int32)
            m_b = C // ps
            span = min(n_span, self.kv.pages_for(p) - m_b)
            pages[:span] = self.kv.table[s, m_b:m_b + span]
            self.kv.pools = self._prefix_pack_fn(Lb)(
                self.kv.pools, kv_suffix, jnp.asarray(pages),
                jnp.asarray(C % ps, np.int32))
            self._tr_end(req.req_id)
        else:
            Lb = self.bucket_for(p)
            self._tr_begin(req.req_id, "prefill", bucket=Lb)
            ids = np.zeros((1, Lb), np.int32)
            ids[0, :p] = req.prompt_ids
            last, kv_prompt = self._prefill_fn(Lb)(
                self.params, jnp.asarray(ids),
                jnp.asarray([p], np.int32))
            tok0 = int(np.asarray(pick_next(
                last, jnp.asarray(keys[0]),
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, is_probs=self._probs))[0])

            pages = np.zeros(Lb // ps, np.int32)   # 0 = trash for pad
            n_real = self.kv.pages_for(p)
            pages[:n_real] = self.kv.table[s, :n_real]
            self.kv.pools = self._pack_fn(Lb)(self.kv.pools, kv_prompt,
                                              jnp.asarray(pages))
            self._tr_end(req.req_id)
        self._admit_seq += 1
        sl = _Slot(req, keys, pos=p, first_tok=tok0,
                   admit_seq=self._admit_seq)
        self.slots[s] = sl
        self._slots_dirty = True
        self.flight.record("admit", req=str(req.req_id), slot=s,
                           bucket=Lb, prompt_len=p,
                           pages=int(self.kv.pages_for(p)))
        self._begin_stream(s, tok0)

    def _count_prefix(self, req: Request, C: int, n_pp: int, p: int) -> None:
        """Prefix-index hit/miss accounting shared by both admission
        paths (chunked admission counts the SAME tokens-saved: the first
        `C` prompt tokens never take a chunk row)."""
        if self.prefix is None:
            return
        if C > 0:
            self.n_prefix_hits += 1
            self.prefill_tokens_saved += C
            self._tr_instant(req.req_id, "prefix_hit", n_pages=n_pp,
                             tokens=C)
            self.flight.record("prefix_hit", req=str(req.req_id),
                               pages=n_pp, tokens=C, suffix=p - C)
        else:
            self.n_prefix_misses += 1
            self.flight.record("prefix_miss", req=str(req.req_id),
                               prompt_len=int(p))

    def _begin_stream(self, s: int, tok0: int) -> None:
        """Stream token 0 of a freshly-prefilled slot (legacy one-dispatch
        prefill or the mixed step's final chunk): open the decode/replay
        lifecycle phase, fire on_token(.., 0), retire on eos/max_new=1."""
        sl = self.slots[s]
        req = sl.req
        stash = req._preempted_gen or []
        if stash:
            # tokens 0..len(stash)-1 re-emit deterministically — a replay
            # span until the first fresh token (step()'s flip)
            sl.replay_until = len(stash)
            self._tr_begin(req.req_id, "replay", replays=len(stash))
        else:
            self._tr_begin(req.req_id, "decode")
        self.tokens_generated += 1
        if self.on_token is not None:
            self.on_token(req.req_id, tok0, 0)
        if tok0 == req.eos_id or req.max_new == 1:
            self._retire(s)

    def _admit_chunked(self, s: int, req: Request, C: int = 0,
                       n_pp: int = 0) -> None:
        """Chunk-granular admission — NO prefill dispatch: the slot enters
        PREFILL mode (gen=0) with its prompt pages already reserved, and
        the prompt commits in `prefill_chunk`-token rows inside the next
        mixed steps (_run_mixed_step).  A prefix hit just means the first
        `C` tokens are already mapped — the chunk cursor starts at C, and
        a mid-page start writes into the boundary page _reserve COW'd.
        Token 0 is sampled by the step that runs the FINAL chunk; until
        then the slot emits nothing."""
        self._tr_end(req.req_id)                       # queued ends here
        p = req.prompt_ids.size
        keys = np.asarray(jax.random.split(req.rng, req.max_new))
        self._count_prefix(req, C, n_pp, p)
        self._admit_seq += 1
        self.slots[s] = _Slot(req, keys, pos=C, first_tok=None,
                              admit_seq=self._admit_seq)
        self._slots_dirty = True
        self._tr_begin(req.req_id, "prefill",
                       chunk=int(self.prefill_chunk), prompt_len=p,
                       prefix_tokens=C)
        self.flight.record("admit", req=str(req.req_id), slot=s,
                           prompt_len=p, chunk=int(self.prefill_chunk),
                           prefix_tokens=C,
                           pages=int(self.kv.pages_for(p)))

    def _emit_first(self, s: int, tok0: int) -> None:
        """Final-chunk emission: the slot's whole prompt is committed and
        `tok0` was sampled from the last prompt position's logits with
        keys[0] — the exact decision the legacy one-dispatch prefill
        made.  Flips the slot into decode mode and streams token 0."""
        sl = self.slots[s]
        sl.gen = 1
        sl.last_tok = tok0
        sl.generated = [tok0]
        self._tr_end(sl.req.req_id)                    # prefill ends here
        self._begin_stream(s, tok0)

    def _preempt(self, s: int) -> None:
        sl = self.slots[s]
        rid = sl.req.req_id
        self._tr_end(rid, tokens=sl.gen)      # decode/replay ends here
        self._tr_instant(rid, "preempt")
        self._tr_begin(rid, "queued", requeued=True)
        self.queue.appendleft(sl.req)
        old = sl.req._preempted_gen or []
        if len(sl.generated) >= len(old):     # a re-preempt mid-replay
            sl.req._preempted_gen = list(sl.generated)  # keeps the longer
        self.tokens_generated -= sl.gen       # the restart re-emits them
        self._bump_attr(rid, "preempts")
        self.n_preemptions += 1
        self.flight.record("preempt", req=str(rid), slot=s,
                           tokens=sl.gen,
                           free_pages=int(self.kv.free_page_count))
        # donate before releasing: the victim's committed pages become
        # cached refcount-zero (evictable under the very pressure that
        # caused this preempt), and its re-admission prefix-hits its own
        # prompt — the deterministic replay skips the prefill it already
        # paid for
        self._donate(s)
        self.kv.release(s)
        self.slots[s] = None
        self._slots_dirty = True

    def _donate(self, s: int) -> None:
        """Offer the slot's fully-committed clean pages to the prefix
        index (retire/preempt/abort).  Only WHOLE pages strictly below
        `pos` qualify — every position in them holds committed K/V; the
        partial boundary page (and the not-yet-written last token) stay
        private and free normally.  The index retains via the allocator's
        cached mark, so the subsequent release drops these pages to
        cached-only instead of freeing them."""
        if self.prefix is None:
            return
        sl = self.slots[s]
        full = int(sl.pos) // self.kv.page_size
        if full <= 0:
            return
        seq = np.concatenate([sl.req.prompt_ids,
                              np.asarray(sl.generated, np.int32)])
        self.prefix.insert(seq[:full * self.kv.page_size],
                           [int(self.kv.table[s, j]) for j in range(full)])

    def reset_prefix_cache(self) -> None:
        """Full allocator cold start (idle engine only): release every
        slot mapping, forget all prefix retention, rebuild the free list
        in canonical order (kv.reset) AND clear the index — page
        placement afterwards is bit-reproducible across engine restarts
        (exactness tests and postmortem engine.json snapshots stay
        stable)."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "reset_prefix_cache requires an idle engine"
        self.kv.reset()
        if self.prefix is not None:
            self.prefix.clear()

    def set_chunking(self, prefill_chunk: Optional[int],
                     max_step_tokens: Optional[int] = None) -> None:
        """Configure chunked prefill (idle engine only — a live slot may
        be mid-chunk).  `prefill_chunk=None` disables chunking: prompts
        prefill through the legacy bucketed one-dispatch paths — the
        baseline side of bench_serving's heavy-tail A/B.  Enabled (the
        default: 4*page_size), prompts commit in chunk rows inside the
        mixed step under `max_step_tokens` (default prefill_chunk +
        num_slots): one row per decoding slot plus up to prefill_chunk
        rows per chunking prompt, never more than the budget per step —
        the p99 inter-token bound.  Each distinct max_step_tokens value
        is one mixed-step signature; hold it fixed in production."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "set_chunking requires an idle engine"
        self._mst_explicit = max_step_tokens is not None
        if prefill_chunk is None:
            self.prefill_chunk = None
            self.max_step_tokens = 0
            return
        prefill_chunk = int(prefill_chunk)
        if prefill_chunk <= 0:
            raise ValueError(
                f"prefill_chunk must be positive (or None to disable "
                f"chunking), got {prefill_chunk}")
        prefill_chunk = min(prefill_chunk, self.kv.capacity_tokens)
        S = len(self.slots)
        mst = self._default_budget(prefill_chunk) \
            if max_step_tokens is None else int(max_step_tokens)
        if mst <= S:
            raise ValueError(
                f"max_step_tokens {mst} must exceed num_slots {S}: every "
                f"decoding slot takes one row per step, and prefill "
                f"chunks need at least one row of headroom to ever make "
                f"progress")
        self.prefill_chunk = prefill_chunk
        self.max_step_tokens = mst

    def _default_budget(self, prefill_chunk: int) -> int:
        """The defaulted token budget: one chunk of prefill headroom
        plus a FULL chain per slot — `chunk + S` with speculation off
        (the classic default), `chunk + S*(spec_k+1)` with it on, so a
        default deployment's draft depth is never silently throttled to
        the chunk headroom (the bench pins the same formula)."""
        return prefill_chunk + len(self.slots) * (
            int(getattr(self, "spec_k", 0)) + 1)

    def set_speculation(self, spec_k: int, drafter=None,
                        dynamic: Optional[bool] = None) -> None:
        """Configure speculative decoding (idle engine only — a live
        chain would straddle the toggle).  `spec_k=0` disables — the
        baseline side of bench_serving's --spec-k A/B; `spec_k > 0`
        drafts up to k lookahead tokens per decoding slot per step
        (serving/drafter.py's prompt-lookup NgramDrafter by default;
        pass `drafter` for anything with a `.propose(ctx, k)` — a
        ModelDrafter slots in here and additionally gets the batched
        `propose_batch` path).  Emitted tokens are IDENTICAL either way;
        only steps-per-token changes.  Each distinct (token budget,
        spec_k) pair is ONE verify-step signature — hold both fixed in
        production.  `dynamic=True` turns on the per-slot EWMA depth
        policy (see `_dyn_k`); it changes HOST-side slicing only, so it
        adds zero signatures and — by the verify step's exactness — zero
        token differences."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "set_speculation requires an idle engine"
        spec_k = int(spec_k)
        if spec_k < 0:
            raise ValueError(
                f"spec_k must be >= 0 (0 = speculation off), got {spec_k}")
        self.spec_k = spec_k
        if dynamic is not None:
            self.spec_dynamic = bool(dynamic)
        if self.prefill_chunk is not None and not self._mst_explicit:
            # a DEFAULTED budget follows the speculation depth (chunk +
            # S*(k+1)): otherwise `--spec-k` deployments would silently
            # throttle draft rows to the chunk headroom, and the banked
            # bench number would not represent a default deployment.
            # An explicit budget is the operator's pin — untouched.
            self.max_step_tokens = self._default_budget(
                self.prefill_chunk)
        if drafter is not None:
            self.drafter = drafter
        elif self.drafter is None and spec_k > 0:
            from paddle_tpu.serving.drafter import NgramDrafter
            self.drafter = NgramDrafter()
        # the eos clamp rides propose(ctx, k, eos_id=...) — but drafters
        # predate that parameter (tests and deployments define 2-arg
        # propose), so sniff the signature ONCE here, not per proposal
        self._drafter_takes_eos = False
        if self.drafter is not None and \
                not hasattr(self.drafter, "propose_batch"):
            import inspect
            try:
                self._drafter_takes_eos = "eos_id" in \
                    inspect.signature(self.drafter.propose).parameters
            except (TypeError, ValueError):
                self._drafter_takes_eos = False

    @property
    def drafter_kind(self) -> Optional[str]:
        """The configured drafter's self-declared kind ("ngram",
        "model", ... — stats/hello frames report it), or None."""
        return getattr(self.drafter, "kind", None) \
            if self.drafter is not None else None

    def set_decode_mode(self, mode: str) -> None:
        """Configure the step() dispatch policy (idle engine only, like
        every dispatch knob).  "auto" (the default) picks per flush
        window between the spec verify step, the pure-decode k-step
        scan, and the mixed step — speculation and multi-step COMPOSE: a
        window where the drafter proposes runs the verify step, a
        draft-free pure-decode window runs the scan, and filling slots
        drop to the mixed step so admissions never stall.  "static"
        keeps the legacy exclusivity (spec_k > 0 disables the scan) for
        apples-to-apples A/B against pre-auto behavior.  Tokens are
        bit-identical across modes — this chooses dispatch shapes, never
        content — which is also why checkpoints deliberately do not pin
        it (restore composes with either mode, like decode_steps)."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "set_decode_mode requires an idle engine"
        if mode not in ("auto", "static"):
            raise ValueError(
                f"decode_mode must be 'auto' or 'static', got {mode!r}")
        self.decode_mode = mode

    def set_decode_steps(self, decode_steps: int) -> None:
        """Configure multi-step decode (idle engine only — a live slot's
        host mirrors must be at a scan boundary).  `decode_steps=1`
        disables — the baseline side of bench_serving's --decode-steps
        A/B; k > 1 runs up to k decode bodies per dispatch inside ONE
        jitted lax.scan whenever the engine is pure-decode.  Emitted
        tokens are IDENTICAL either way; only dispatches-per-token (and
        the streaming burst size) change.  Each distinct k is ONE scanned
        signature per slot count — hold it fixed in production."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "set_decode_steps requires an idle engine"
        decode_steps = int(decode_steps)
        if decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1 (1 = multi-step off), got "
                f"{decode_steps}")
        self.decode_steps = decode_steps

    @property
    def spec_accept_rate(self) -> float:
        """Accepted / drafted over the engine lifetime (0.0 before any
        draft was scored) — the number PERF.md 'Reading the accept
        rate' interprets."""
        return (self.n_spec_accepted / self.n_spec_drafted
                if self.n_spec_drafted else 0.0)

    def set_prefix_cache(self, enabled: bool) -> None:
        """A/B knob (bench_serving --prefix-skew measures the same engine
        with the cache off, then on): disabling detaches AND empties the
        index — every node's page drops its cached retention, so pages
        still mapped by live slots stay with their slots and free through
        the normal release flow — leaving nothing for a baseline run to
        match; enabling attaches a fresh empty index."""
        if enabled == (self.prefix is not None):
            return
        if enabled:
            self.prefix = PrefixTree(self.kv)
            self.kv.on_page_pressure = self.prefix.evict_for
            return
        stack = list(self.prefix.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.host_id is not None:
                # spilled nodes drain the HOST tier, not the device
                # allocator — leaving the entry would orphan host bytes
                # against the budget forever (no node names them again)
                self.kv.drop_host_page(node.host_id, reason="drain")
                node.host_id = None
            else:
                self.kv.uncache_page(node.page)
        self.prefix = None
        self.kv.on_page_pressure = None

    def set_spill_budget(self, spill_bytes_budget: int) -> None:
        """A/B knob (bench_serving --spill-budget measures the same
        engine spill-off, then on): sets the host tier's byte budget.
        Shrinking below current residency drops LRU HOST leaves until
        the tier fits (0 drains it entirely) — never device state, so
        an idle-engine flip is allocator-exact either way."""
        assert all(sl is None for sl in self.slots) and not self.queue, \
            "set_spill_budget requires an idle engine"
        self.kv.spill_bytes_budget = int(spill_bytes_budget or 0)
        while self.prefix is not None and \
                self.kv.host_bytes > self.kv.spill_bytes_budget:
            leaves = self.prefix._host_leaves()
            assert leaves, "host tier non-empty but no HOST leaf found"
            self.prefix._drop_host_node(
                min(leaves, key=lambda n: n.last_use))

    # -- serving-state checkpoint/restore (fleet-migration primitive) ------
    def checkpoint_state(self) -> dict:
        """Freeze the ENTIRE serving state MID-FLIGHT — device pytree
        (pools as host copies), allocator, slots, queue, prefix index,
        scheduling counters — as one picklable dict.  A fresh engine of
        the same configuration restored from it resumes and finishes
        BIT-EXACTLY what the uninterrupted engine would have produced
        (tests/test_engine_state.py): per-slot key schedules, admit_seq
        preemption order, free-list order and page placement all survive.
        Call between steps on the step()-driving thread (the pump), like
        every other scheduler access.  This is the checkpoint/restore +
        live-replica-migration unit the EngineState refactor unlocks.

        Multi-step decode needs no special handling: a scanned dispatch
        is atomic INSIDE step(), so between steps the engine is always at
        a scan boundary — host mirrors converged, no mid-window state
        exists to freeze.  `decode_steps` is deliberately NOT part of the
        config-match dict: it is an A/B dispatch knob, and a snapshot
        taken under k restores bit-exactly onto an engine running any
        other k (tests/test_multi_step.py proves it)."""

        def req_snap(r: Request) -> dict:
            return {"req_id": r.req_id, "prompt_ids": r.prompt_ids.copy(),
                    "max_new": r.max_new, "temperature": r.temperature,
                    "top_k": r.top_k, "top_p": r.top_p, "eos_id": r.eos_id,
                    "deadline": r.deadline, "trace": r.trace,
                    "preempted_gen": (None if r._preempted_gen is None
                                      else list(r._preempted_gen)),
                    "rng": np.asarray(r.rng).copy()}

        kv = self.kv
        prefix = None
        if self.prefix is not None:
            nodes = []
            stack = [(self.prefix.root, -1)]
            while stack:
                node, pidx = stack.pop()
                idx = len(nodes)
                nodes.append({"run": list(node.run), "page": node.page,
                              "host_id": node.host_id,
                              "last_use": node.last_use, "parent": pidx})
                stack.extend((ch, idx) for ch in node.children.values())
            prefix = {"nodes": nodes, "clock": self.prefix._clock,
                      "n_evictions": self.prefix.n_evictions}
        return {
            "config": {"num_slots": len(self.slots),
                       "page_size": kv.page_size,
                       "pages_per_slot": kv.pages_per_slot,
                       "num_pages": kv.num_pages,
                       "prefill_chunk": self.prefill_chunk,
                       "max_step_tokens": self.max_step_tokens,
                       "spec_k": self.spec_k,
                       "prefix_cache": self.prefix is not None,
                       "spill_bytes_budget": kv.spill_bytes_budget,
                       "layer_specs": dict(kv.layer_specs)},
            "pools": {name: {p: np.asarray(kv.pools[name][p]).copy()
                             for p in ("k", "v")} for name in kv.pools},
            "kv": {"table": kv.table.copy(), "free": list(kv._free),
                   "n_pages": kv._n_pages.copy(), "ref": kv._ref.copy(),
                   "cached": kv._cached.copy(), "n_cow": kv.n_cow,
                   # host spill tier SERIALIZES INTO the bundle (the
                   # documented choice over re-faulting: a migrated
                   # replica keeps its whole effective cache, and the
                   # spilled runs' restore-on-hit stays bit-exact on the
                   # target) — generations re-stamp on restore
                   "host": {hid: {"nbytes": e["nbytes"],
                                  "data": {name: (k.copy(), v.copy())
                                           for name, (k, v)
                                           in e["data"].items()}}
                            for hid, e in kv._host.items()},
                   "next_hid": kv._next_hid,
                   "spill_counters": (kv.n_spilled, kv.n_restored,
                                      kv.n_host_evicted,
                                      kv._host_drained)},
            "slots": [None if sl is None else
                      {"req": req_snap(sl.req),
                       "keys": np.asarray(sl.keys).copy(),
                       "pos": int(sl.pos), "gen": int(sl.gen),
                       "last_tok": int(sl.last_tok),
                       "generated": list(sl.generated),
                       "admit_seq": int(sl.admit_seq),
                       "replay_until": int(sl.replay_until),
                       # dynamic-speculation estimate rides the slot: a
                       # migrated replica keeps its learned per-slot k
                       # instead of re-probing from cold
                       "accept_ewma": sl.accept_ewma,
                       "probe_tick": int(sl.probe_tick)}
                      for sl in self.slots],
            "queue": [req_snap(r) for r in self.queue],
            "prefix": prefix,
            "counters": {k: getattr(self, k) for k in (
                "_admit_seq", "n_decode_steps", "n_preemptions",
                "n_cancelled", "n_expired", "tokens_generated",
                "occupancy_sum", "n_prefix_hits", "n_prefix_misses",
                "prefill_tokens_saved", "n_restore_hits",
                "restore_tokens_saved", "n_prefill_chunks",
                "n_mixed_steps", "n_spec_steps", "n_spec_chains",
                "n_spec_drafted", "n_spec_accepted", "n_spec_tokens",
                "n_scan_steps", "n_scan_flushes", "n_draft_steps")},
            "results": {k: np.asarray(v).copy()
                        for k, v in self.results.items()},
            "finish_reasons": dict(self.finish_reasons),
        }

    def restore_state(self, snap: dict) -> None:
        """Resume a `checkpoint_state()` snapshot on THIS engine (fresh or
        idle; its construction-time configuration must match the donor's
        — restoring onto a differently-shaped engine would silently
        corrupt page accounting, so it raises instead).  Device state
        re-uploads lazily through the ordinary dirty-sync paths."""
        cfg = snap["config"]
        mine = {"num_slots": len(self.slots),
                "page_size": self.kv.page_size,
                "pages_per_slot": self.kv.pages_per_slot,
                "num_pages": self.kv.num_pages,
                "prefill_chunk": self.prefill_chunk,
                "max_step_tokens": self.max_step_tokens,
                "spec_k": self.spec_k,
                "prefix_cache": self.prefix is not None,
                "spill_bytes_budget": self.kv.spill_bytes_budget,
                "layer_specs": dict(self.kv.layer_specs)}
        if mine != cfg:
            diff = {k: (cfg[k], mine[k]) for k in cfg if cfg[k] != mine[k]}
            raise ValueError(
                f"restore_state: engine configuration mismatch "
                f"(snapshot vs this engine): {diff}")
        if any(sl is not None for sl in self.slots) or self.queue:
            raise ValueError("restore_state requires an idle engine — it "
                             "replaces every slot and queue entry")

        def req_restore(d: dict) -> Request:
            r = Request(d["req_id"], d["prompt_ids"],
                        max_new=d["max_new"], temperature=d["temperature"],
                        top_k=d["top_k"], top_p=d["top_p"],
                        eos_id=d["eos_id"], deadline=d["deadline"],
                        trace=d.get("trace"))
            r.rng = jnp.asarray(d["rng"])
            r._preempted_gen = (None if d["preempted_gen"] is None
                                else list(d["preempted_gen"]))
            return r

        kv = self.kv
        for name in kv.pools:
            put = ((lambda a: jax.device_put(a, self._pool_sharding))
                   if self._pool_sharding is not None else jnp.asarray)
            dtype = kv.pools[name]["k"].dtype
            kv.pools[name] = {
                p: put(np.asarray(snap["pools"][name][p], dtype))
                for p in ("k", "v")}
        kv.table[:, :] = snap["kv"]["table"]
        kv._free = list(snap["kv"]["free"])
        kv._n_pages[:] = snap["kv"]["n_pages"]
        kv._ref[:] = snap["kv"]["ref"]
        kv._cached[:] = snap["kv"]["cached"]
        kv.n_cow = snap["kv"]["n_cow"]
        # host spill tier: adopt the bundle's entries under THIS engine's
        # current generation (the donor's gen counter is process-local;
        # every serialized entry was live by construction — its tree node
        # rebuilds below and names it).  Drain any pre-restore tree FIRST
        # — its nodes' hids would otherwise collide with the bundle's hid
        # space when the post-rebuild clear() walks them
        if self.prefix is not None:
            self.prefix.clear()
        kv._host_drained += len(kv._host)
        kv._host = {int(hid): {"gen": kv._host_gen,
                               "nbytes": int(e["nbytes"]),
                               "data": {name: (np.asarray(k),
                                               np.asarray(v))
                                        for name, (k, v)
                                        in e["data"].items()}}
                    for hid, e in snap["kv"].get("host", {}).items()}
        kv._host_bytes = sum(e["nbytes"] for e in kv._host.values())
        kv._next_hid = int(snap["kv"].get("next_hid", kv._next_hid))
        (kv.n_spilled, kv.n_restored, kv.n_host_evicted,
         kv._host_drained) = snap["kv"].get(
            "spill_counters", (kv.n_spilled, kv.n_restored,
                               kv.n_host_evicted, kv._host_drained))
        kv.version += 1
        self.slots = [None if d is None else
                      _Slot.__new__(_Slot) for d in snap["slots"]]
        for sl, d in zip(self.slots, snap["slots"]):
            if sl is None:
                continue
            sl.req = req_restore(d["req"])
            sl.keys = np.asarray(d["keys"], np.uint32)
            sl.pos, sl.gen = d["pos"], d["gen"]
            sl.last_tok = d["last_tok"]
            sl.generated = list(d["generated"])
            sl.admit_seq = d["admit_seq"]
            sl.replay_until = d["replay_until"]
            sl.accept_ewma = d.get("accept_ewma")
            sl.probe_tick = int(d.get("probe_tick", 0))
        self.queue = deque(req_restore(d) for d in snap["queue"])
        if self.prefix is not None:
            self.prefix.clear()
            if snap["prefix"] is not None:
                from paddle_tpu.serving.prefix_tree import _Node
                built = []
                for nd in snap["prefix"]["nodes"]:
                    node = _Node(tuple(nd["run"]), nd["page"],
                                 None if nd["parent"] < 0
                                 else built[nd["parent"]])
                    node.host_id = nd.get("host_id")
                    node.last_use = nd["last_use"]
                    if node.parent is not None:
                        node.parent.add_child(node)
                    built.append(node)
                self.prefix.root = built[0]
                self.prefix.n_nodes = len(built) - 1
                self.prefix._clock = snap["prefix"]["clock"]
                self.prefix.n_evictions = snap["prefix"]["n_evictions"]
        for k, v in snap["counters"].items():
            setattr(self, k, v)
        self.results = {k: np.asarray(v).copy()
                        for k, v in snap["results"].items()}
        self.finish_reasons = dict(snap["finish_reasons"])
        self._slots_dirty = True
        self._run_host = None
        self._t_prev_decode = None
        # latency attribution across a migration: perf_counter epochs are
        # per-process, so pre-restore phase clocks cannot carry over —
        # re-open each live request's CURRENT phase at now (the breakdown
        # charges post-restore time only; the donor's time was reported
        # by the donor had it finished there)
        now = time.perf_counter()
        self._req_phase = {}
        self._req_attr = {}
        self._req_trace = {}
        for r in self.queue:
            self._req_phase[r.req_id] = ("queued", now)
            if r.trace:
                self._req_trace[r.req_id] = r.trace
        for sl in self.slots:
            if sl is None:
                continue
            phase = ("prefill" if sl.gen == 0 else
                     "replay" if sl.replay_until and
                     sl.gen < sl.replay_until else "decode")
            self._req_phase[sl.req.req_id] = (phase, now)
            if sl.req.trace:
                self._req_trace[sl.req.req_id] = sl.req.trace
        kv.check()                      # allocator oracle on the restored
                                        # tables/refcounts — fail loudly
        self.flight.record("restore", slots=sum(
            1 for sl in self.slots if sl is not None),
            queued=len(self.queue))

    def save_state(self, path: str) -> None:
        """checkpoint_state() to disk with the repo's atomic-commit
        discipline (stage + fsync + os.replace): a crash mid-save leaves
        the previous checkpoint intact, never a torn one."""
        import os
        import pickle

        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self.checkpoint_state(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load_state(self, path: str) -> None:
        import pickle

        with open(path, "rb") as f:
            self.restore_state(pickle.load(f))

    def _retire(self, s: int) -> None:
        sl = self.slots[s]
        toks = np.concatenate(
            [sl.req.prompt_ids,
             np.asarray(sl.generated, np.int32)]).astype(np.int32)
        reason = "stop" if sl.last_tok == sl.req.eos_id else "length"
        self._donate(s)
        self.kv.release(s)
        self.slots[s] = None
        self._slots_dirty = True
        self._finish(sl.req.req_id, toks, reason)

    def _finish(self, req_id, toks: np.ndarray, reason: str) -> None:
        # close whatever lifecycle phase is open (queued for an aborted
        # waiter, decode/replay for an in-slot finish) and mark the
        # terminal event: done (stop/length), cancelled, or deadline
        self._tr_end(req_id, reason=reason)
        self._tr_instant(req_id,
                         "done" if reason in ("stop", "length") else reason,
                         reason=reason, tokens=int(toks.size))
        self.flight.record("finish", req=str(req_id), reason=reason,
                           tokens=int(toks.size))
        self.finish_timing[req_id] = self._finish_timing(req_id)
        self._req_trace.pop(req_id, None)
        self.results[req_id] = toks
        self.finish_reasons[req_id] = reason
        if self.on_finish is not None:
            self.on_finish(req_id, toks, reason)

    # -- compiled pieces --------------------------------------------------
    def _slot_keys(self, st: EngineState) -> jnp.ndarray:
        """Each slot's key for THIS step: keys[s, gen[s]] — key g samples
        token g, so a paused slot (gen frozen) consumes nothing and a
        final prompt chunk (gen still 0) samples with keys[0], exactly the
        legacy prefill decision."""
        g = jnp.clip(st.gen, 0, st.keys.shape[1] - 1)
        return jnp.take_along_axis(st.keys, g[:, None, None], axis=1)[:, 0]

    def _decode_impl(self, params, st: EngineState, run):
        """THE decode step — one signature for the whole workload: every
        slot advances one token against its paged context; per-slot
        knobs/keys make sampling data-dependent, not program-dependent.
        A pure function over the EngineState pytree: slots the run mask
        marks advance pos/gen/last-token ON DEVICE (non-running slots'
        sampled values are computed-and-discarded garbage — their rows are
        batch-independent and their writes land in the trash page)."""
        S = st.toks.shape[0]
        table = st.table[:S]                  # drop the virtual trash row
        state = {name: {"k_pages": st.pools[name]["k"],
                        "v_pages": st.pools[name]["v"],
                        "page_table": table, "pos": st.pos}
                 for name in st.pools}
        feed = {self.input_name: Argument(ids=st.toks[:, None],
                                          lengths=jnp.ones((S,), jnp.int32))}
        outputs, _, state_out = self.executor.forward(params, feed, state,
                                                      TEST, None)
        last = outputs[self.logits_name].value[:, 0, :]
        nxt = pick_next_per_slot(last, self._slot_keys(st), st.temp,
                                 st.topk, st.topp, is_probs=self._probs)
        new_pools = {name: {"k": state_out[name]["k_pages"],
                            "v": state_out[name]["v_pages"]}
                     for name in st.pools}
        runi = run.astype(jnp.int32)
        new_st = EngineState(pools=new_pools, table=st.table,
                             pos=st.pos + runi,
                             toks=jnp.where(run, nxt, st.toks),
                             gen=st.gen + runi, keys=st.keys, temp=st.temp,
                             topk=st.topk, topp=st.topp)
        return new_st, nxt

    def _scan_impl(self, k: int, params, st: EngineState, run, eos,
                   maxnew):
        """THE scanned decode step — one signature per (S, k): k
        applications of the EXACT k=1 body (_decode_impl) chained through
        the donated EngineState by lax.scan, with per-slot retirement ON
        DEVICE: after each body, a slot whose sampled token hit its eos
        id or whose generation count reached max_new drops out of the run
        mask, so its later iterations recompute with frozen pos/toks —
        batch-independent garbage whose K/V write lands at the one
        uncommitted position after its last token (never read, never
        donated to the prefix index).  The [k, S] stacked samples are the
        host boundary's token block; rows past a slot's retirement are
        discarded by the host cut that mirrors this very mask."""
        def body(carry, _):
            st, run = carry
            new_st, nxt = self._decode_impl(params, st, run)
            run = run & (nxt != eos) & (new_st.gen < maxnew)
            return (new_st, run), nxt
        (new_st, _), toks = jax.lax.scan(body, (st, run), None, length=k)
        return new_st, toks

    def _scan_step_fn(self):
        """The jitted scanned step (signature discipline: ONE scanned
        program per (S, k)) — `k` rides as a STATIC leading argument so
        one jit object holds every window length, its cache size counts
        the programs directly, and the compile watcher's signature at
        site `serving.scan_step` distinguishes k (static ints are part
        of the call signature, where a partial-bound k would vanish
        from the aval-only view) — the recompile-storm detector sees a
        knob-churning deployment the same way it sees bucket churn."""
        if self._scan_step is None:
            scan_jit = jax.jit(self._scan_impl, static_argnums=(0,),
                               donate_argnums=(2,),
                               **self._step_sharding_kwargs(n_extra=3))
            self._scan_step = get_compile_watch().wrap_jit(
                "serving.scan_step", scan_jit)
        return self._scan_step

    def _mixed_impl(self, params, st: EngineState, row_ids, row_slot,
                    row_pos, sample_row, adv, emit):
        """THE mixed prefill/decode step — one signature per
        max_step_tokens value, whatever the prefill/decode row mix: the
        packed ragged token rows run the stack as one [1, T] batch (every
        non-attention layer is per-token; attention routes through
        layers_attn._paged_ragged_step via the `row_slot` cache marker),
        then per-slot sampling reads each slot's designated logits row.
        `adv`/`emit` are the host scheduler's advance masks: pos moves by
        the rows each slot committed, gen/last-token move where a token
        was banked (decode rows and final chunks).  Non-emitting slots
        (mid-prefill, paused, empty) sample a padding/decode row's logits
        — computed and discarded, their state frozen by the masks."""
        T = row_ids.shape[0]
        state = {name: {"k_pages": st.pools[name]["k"],
                        "v_pages": st.pools[name]["v"],
                        "page_table": st.table, "row_slot": row_slot,
                        "row_pos": row_pos}
                 for name in st.pools}
        feed = {self.input_name: Argument(
            ids=row_ids[None, :], lengths=jnp.full((1,), T, jnp.int32))}
        outputs, _, state_out = self.executor.forward(params, feed, state,
                                                      TEST, None)
        logits = outputs[self.logits_name].value[0]    # [T, V]
        last = logits[sample_row]                      # [S, V]
        nxt = pick_next_per_slot(last, self._slot_keys(st), st.temp,
                                 st.topk, st.topp, is_probs=self._probs)
        new_pools = {name: {"k": state_out[name]["k_pages"],
                            "v": state_out[name]["v_pages"]}
                     for name in st.pools}
        new_st = EngineState(pools=new_pools, table=st.table,
                             pos=st.pos + adv,
                             toks=jnp.where(emit, nxt, st.toks),
                             gen=st.gen + emit.astype(jnp.int32),
                             keys=st.keys, temp=st.temp, topk=st.topk,
                             topp=st.topp)
        return new_st, nxt

    def _spec_impl(self, params, st: EngineState, row_ids, row_slot,
                   row_pos, first_row, n_draft, draft_toks, spec, emit,
                   adv_chunk):
        """THE speculative verify step — one signature per (token
        budget, spec_k), whatever the chain/chunk row mix: the packed
        ragged rows run the stack exactly like the mixed step (all K/V
        scattered before the read, so draft rows see each other
        causally), then every slot samples its k+1-position CHAIN —
        position i's logits row is `first_row[s] + i` and its key is
        `keys[s, gen[s] + i]` (sampler.py pick_next_chain), making
        sample i bit-equal to the token the sequential engine would
        emit at generation gen+i given the prefix matched.

        Acceptance on device: `acc[s]` = leading run of draft agreement
        (`sampled[:, :k] == draft_toks`, masked to the real draft
        count), and chain slots commit acc+1 tokens — pos/gen advance
        by it, last-token becomes sampled[s, acc] (the first
        non-drafted sample: the bonus token on full acceptance, the
        corrected token on a rejection).  Chunk slots advance by their
        host-scheduled masks exactly as in the mixed step.  Rejected
        rows' K/V stays in the pools as causally-invisible garbage the
        next chain overwrites — the device needs no rollback; the host
        returns the unjustified page tail (kv.uncommit_tail).

        Returns (state', sampled [S, k+1], acc [S])."""
        T = row_ids.shape[0]
        S = st.toks.shape[0]
        K = draft_toks.shape[1]
        state = {name: {"k_pages": st.pools[name]["k"],
                        "v_pages": st.pools[name]["v"],
                        "page_table": st.table, "row_slot": row_slot,
                        "row_pos": row_pos}
                 for name in st.pools}
        feed = {self.input_name: Argument(
            ids=row_ids[None, :], lengths=jnp.full((1,), T, jnp.int32))}
        outputs, _, state_out = self.executor.forward(params, feed, state,
                                                      TEST, None)
        logits = outputs[self.logits_name].value[0]    # [T, V]
        idx = jnp.clip(first_row[:, None] + jnp.arange(K + 1)[None, :],
                       0, T - 1)
        chain = logits[idx]                            # [S, K+1, V]
        g = jnp.clip(st.gen[:, None] + jnp.arange(K + 1)[None, :], 0,
                     st.keys.shape[1] - 1)
        keys = st.keys[jnp.arange(S)[:, None], g]      # [S, K+1, 2]
        sampled = pick_next_chain(chain, keys, st.temp, st.topk,
                                  st.topp, is_probs=self._probs)
        ok = jnp.logical_and(sampled[:, :K] == draft_toks,
                             jnp.arange(K)[None, :] < n_draft[:, None])
        acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
        n_new = (acc + 1) * spec.astype(jnp.int32)
        committed = jnp.where(spec, n_new, adv_chunk)
        gen_adv = jnp.where(spec, n_new, emit.astype(jnp.int32))
        last = sampled[jnp.arange(S), acc]
        toks_new = jnp.where(spec, last,
                             jnp.where(emit, sampled[:, 0], st.toks))
        new_pools = {name: {"k": state_out[name]["k_pages"],
                            "v": state_out[name]["v_pages"]}
                     for name in st.pools}
        new_st = EngineState(pools=new_pools, table=st.table,
                             pos=st.pos + committed, toks=toks_new,
                             gen=st.gen + gen_adv, keys=st.keys,
                             temp=st.temp, topk=st.topk, topp=st.topp)
        return new_st, sampled, acc

    def _prefill_fn(self, Lb: int):
        """Jitted prompt prefill for bucket length Lb — compiled once per
        BUCKET (the feeder's _bucket_len grid), not per prompt length."""
        fn = self._prefill_cache.get(Lb)
        if fn is None:
            executor = self.executor
            input_name, logits_name = self.input_name, self.logits_name
            attn_layers = list(self.kv.pools)

            def prefill(params, ids, n):               # ids [1, Lb], n [1]
                state = init_kv_caches(executor, 1, Lb)
                outputs, _, state = executor.forward(
                    params, {input_name: Argument(ids=ids, lengths=n)},
                    state, TEST, None)
                logits = outputs[logits_name].value
                last = jnp.take_along_axis(
                    logits, (n - 1)[:, None, None], axis=1)[:, 0, :]
                return last, {name: (state[name]["k"], state[name]["v"])
                              for name in attn_layers}

            fn = self._prefill_cache[Lb] = get_compile_watch().wrap_jit(
                "serving.prefill", jax.jit(prefill))
        return fn

    def _pack_fn(self, Lb: int):
        """Jitted page writer: scatter a bucket-length prompt's K/V into
        the slot's pages (page j of the prompt -> physical pages[j]; pad
        pages target the trash page 0)."""
        fn = self._pack_cache.get(Lb)
        if fn is None:
            ps = self.kv.page_size
            n_pages = Lb // ps
            specs = self.kv.layer_specs

            def pack(pools, kv_prompt, pages):
                out = {}
                for name, (h_kv, dh) in specs.items():
                    k, v = kv_prompt[name]
                    out[name] = {
                        "k": pools[name]["k"].at[pages].set(
                            k[0, :Lb].reshape(n_pages, ps, h_kv, dh)
                            .astype(pools[name]["k"].dtype)),
                        "v": pools[name]["v"].at[pages].set(
                            v[0, :Lb].reshape(n_pages, ps, h_kv, dh)
                            .astype(pools[name]["v"].dtype)),
                    }
                return out

            fn = self._pack_cache[Lb] = get_compile_watch().wrap_jit(
                "serving.pack", jax.jit(pack, donate_argnums=(0,),
                                        **self._pools_out_kwargs()))
        return fn

    def _prefix_prefill_fn(self, n_pp: int, Lb: int):
        """Jitted SUFFIX prefill for a prefix-hit admission: gather the
        matched prefix K/V out of `n_pp` pool pages into a dense seed
        cache, then run the stack on the Lb-bucket suffix tokens through
        layers_attn's continuation path (the static "cont" marker routes
        multi-token cached attention through cached_attention_step, which
        scatters at the dynamic offset `c` and masks on global positions).
        Compiled once per (prefix pages, suffix bucket); the matched token
        count `c` and valid suffix length `n` are dynamic operands.
        Returns (last-valid-position logits, per-layer suffix K/V sliced
        at [c, c+Lb) — the shape _prefix_pack_fn scatters)."""
        key = (n_pp, Lb)
        fn = self._prefix_prefill_cache.get(key)
        if fn is None:
            executor = self.executor
            input_name, logits_name = self.input_name, self.logits_name
            specs = self.kv.layer_specs
            ps = self.kv.page_size
            Cpad = n_pp * ps
            dtype = jnp.dtype(executor.compute_dtype) \
                if executor.compute_dtype else jnp.float32

            def prefill(params, pools, ctx_pages, ids, n, c):
                # ctx_pages [n_pp] physical pages; positions [c, Cpad) of
                # the seed hold the boundary page's beyond-match tokens —
                # garbage for THIS request, but cached_attention_step's
                # scatter overwrites [c, c+Lb) before attention and its
                # causal mask never reaches the rest
                state = {}
                for name, (h_kv, dh) in specs.items():
                    seed_k = pools[name]["k"][ctx_pages] \
                        .reshape(1, Cpad, h_kv, dh)
                    seed_v = pools[name]["v"][ctx_pages] \
                        .reshape(1, Cpad, h_kv, dh)
                    state[name] = {
                        "k": jnp.zeros((1, Cpad + Lb, h_kv, dh), dtype)
                        .at[:, :Cpad].set(seed_k),
                        "v": jnp.zeros((1, Cpad + Lb, h_kv, dh), dtype)
                        .at[:, :Cpad].set(seed_v),
                        "pos": c, "cont": (),
                    }
                outputs, _, state = executor.forward(
                    params, {input_name: Argument(ids=ids, lengths=n)},
                    state, TEST, None)
                logits = outputs[logits_name].value
                last = jnp.take_along_axis(
                    logits, (n - 1)[:, None, None], axis=1)[:, 0, :]
                return last, {
                    name: tuple(
                        jax.lax.dynamic_slice_in_dim(state[name][part],
                                                     c[0], Lb, axis=1)
                        for part in ("k", "v"))
                    for name in specs}

            fn = self._prefix_prefill_cache[key] = \
                get_compile_watch().wrap_jit(
                    "serving.prefix_prefill", jax.jit(prefill))
        return fn

    def _prefix_pack_fn(self, Lb: int):
        """Jitted offset page writer: scatter an Lb-token suffix's K/V into
        the slot's pages starting at dynamic in-page offset `off` — token i
        lands in pages[(off + i) // ps] at row (off + i) % ps.  Pages past
        the prompt's real span are the trash page 0 (same padded-bucket
        discipline as the cold pack)."""
        fn = self._prefix_pack_cache.get(Lb)
        if fn is None:
            ps = self.kv.page_size
            specs = self.kv.layer_specs

            def pack(pools, kv_suffix, pages, off):
                idx = off + jnp.arange(Lb)
                phys = pages[idx // ps]                       # [Lb]
                row = idx % ps
                out = {}
                for name in specs:
                    k, v = kv_suffix[name]
                    out[name] = {
                        "k": pools[name]["k"].at[phys, row].set(
                            k[0].astype(pools[name]["k"].dtype)),
                        "v": pools[name]["v"].at[phys, row].set(
                            v[0].astype(pools[name]["v"].dtype)),
                    }
                return out

            fn = self._prefix_pack_cache[Lb] = get_compile_watch().wrap_jit(
                "serving.prefix_pack",
                jax.jit(pack, donate_argnums=(0,),
                        **self._pools_out_kwargs()))
        return fn
