"""Declarative SLOs + multi-window burn-rate alerting over the history ring.

The wedge watchdogs (serving/pserver/router) only freeze a postmortem
bundle when a thread has *already* stopped making progress.  This module
is the earlier tripwire: declarative SLO specs evaluated over the
`obs/timeseries.py` ring, multi-window SRE style — an objective is
"burning" in a window when the fraction of resolution windows that
violated it reaches `burn_threshold`, and a spec FIRES only when both
the short and the long window burn (a transient blip trips neither; a
sustained regression trips both).  Clearing needs only the short window
to recover, so alerts shut off quickly once the fleet is healthy.

On a firing transition the evaluator

  * records an `slo_fire` flight event (and `slo_clear` on recovery),
  * flips the `obs_slo_firing{slo=...}` gauge (and counts the
    transition in `obs_slo_fired_total`),
  * freezes at most ONE postmortem bundle per episode through the same
    re-arm shape as the wedge watchdogs: the dump hook runs when the
    fleet goes from "no SLOs firing" to "some SLO firing", and re-arms
    only when ALL specs have cleared — so degradation produces a bundle
    with the offending series attached *before* anything dies.

Spec kinds:

  * "gauge"     — `series` is one gauge key; each stored point is
                  compared against the objective (p99 TTFT/ITL ride
                  the StatSet quantile gauges this way).
  * "ratio"     — `series`/`den` are counter keys (tuples are summed);
                  per window, ratio = sum(num deltas)/sum(den deltas),
                  and windows with zero denominator are SKIPPED — no
                  traffic burns no budget (an idle fleet never pages).
  * "hist_mean" — sugar over "ratio" for a catalogued histogram:
                  per-window mean = `<series>_sum` / `<series>_count`.

Evaluation runs on the HistorySampler thread right after each sampling
pass; it reads the ring under its lock and touches nothing the pump
owns.  Stdlib-only, like the rest of `obs/`.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

from paddle_tpu.obs.timeseries import MetricHistory


@dataclass
class SloSpec:
    """One declarative objective over the history ring."""

    #: identity — the `slo` label value on obs_slo_firing and the
    #: flight-event payload
    name: str
    #: series key ("gauge"/"hist_mean") or numerator key(s) ("ratio")
    series: object = ""
    #: the objective the windowed value is compared against
    objective: float = 0.0
    #: fires when value OP objective — ">" (latency/skew/shed style) or
    #: "<" (accept-rate/hit-rate style)
    op: str = ">"
    kind: str = "gauge"          # "gauge" | "ratio" | "hist_mean"
    #: ratio denominators (counter keys, summed)
    den: tuple = ()
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    #: fraction of evaluated windows that must violate to burn
    burn_threshold: float = 0.5
    description: str = ""

    def __post_init__(self):
        if self.op not in (">", "<"):
            raise ValueError(f"slo {self.name!r}: op must be '>' or '<'")
        if self.kind not in ("gauge", "ratio", "hist_mean"):
            raise ValueError(f"slo {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        if self.kind == "ratio" and not self.den:
            raise ValueError(f"slo {self.name!r}: ratio needs den")
        if self.long_window_s < self.short_window_s:
            raise ValueError(f"slo {self.name!r}: long window shorter "
                             f"than short window")

    def _num_keys(self) -> tuple:
        if self.kind == "hist_mean":
            return (f"{self.series}_sum",)
        return (self.series,) if isinstance(self.series, str) \
            else tuple(self.series)

    def _den_keys(self) -> tuple:
        if self.kind == "hist_mean":
            return (f"{self.series}_count",)
        return tuple(self.den)


class SloEvaluator:
    """Evaluates SloSpecs against a MetricHistory; owns the per-spec
    firing state and the one-bundle-per-episode dump re-arm."""

    def __init__(self, history: MetricHistory, specs, *, flight=None,
                 registry=None, dump_fn=None):
        self.history = history
        self.specs = list(specs)
        self.flight = flight
        self.dump_fn = dump_fn
        self._firing = {s.name: False for s in self.specs}
        self._last = {}              # spec name -> last windowed value
        self._dumped = False         # one bundle per episode (re-arm
        self._gauge = None           # when ALL specs clear)
        self._counter = None
        if registry is not None and self.specs:
            self._gauge = registry.gauge("obs_slo_firing",
                                         labels=("slo",))
            self._counter = registry.counter("obs_slo_fired_total",
                                             labels=("slo",))
            for s in self.specs:
                self._gauge.set(0.0, slo=s.name)
                self._counter.inc(0.0, slo=s.name)

    # -- reading -----------------------------------------------------------
    def firing(self) -> list[str]:
        return sorted(n for n, f in self._firing.items() if f)

    def status(self) -> list[dict]:
        return [{"slo": s.name, "firing": self._firing[s.name],
                 "objective": s.objective, "op": s.op,
                 "value": self._last.get(s.name),
                 "description": s.description} for s in self.specs]

    # -- evaluation (sampler thread) ---------------------------------------
    def evaluate(self, now: Optional[float] = None) -> list[dict]:
        """One pass over every spec; returns the firing transitions
        ([{"slo", "event", ...}]).  Runs the dump hook on the first fire
        of an episode, AFTER recording the slo_fire event so the bundle
        carries it."""
        now = time.time() if now is None else float(now)
        transitions = []
        first = self.history.first_sample_unix
        for spec in self.specs:
            # warm-up gate: a spec cannot claim its long window burned
            # until the ring has actually covered one — five seconds of
            # uptime is not five minutes of evidence.  (Clearing is
            # never gated; an armed spec may always recover.)
            if not self._firing[spec.name] and \
                    (first == 0.0 or now - first < spec.long_window_s):
                continue
            short = self._burn(spec, spec.short_window_s, now)
            long_ = self._burn(spec, spec.long_window_s, now)
            if short is not None:
                self._last[spec.name] = short[1]
            was = self._firing[spec.name]
            if not was and short is not None and long_ is not None \
                    and short[0] >= spec.burn_threshold \
                    and long_[0] >= spec.burn_threshold:
                self._firing[spec.name] = True
                t = {"slo": spec.name, "event": "slo_fire",
                     "short_burn": round(short[0], 4),
                     "long_burn": round(long_[0], 4),
                     "value": short[1], "objective": spec.objective,
                     "op": spec.op, "series": spec._num_keys()}
                transitions.append(t)
                if self._gauge is not None:
                    self._gauge.set(1.0, slo=spec.name)
                    self._counter.inc(1.0, slo=spec.name)
                if self.flight is not None:
                    self.flight.record(
                        "slo_fire", slo=spec.name,
                        value=short[1], objective=spec.objective,
                        op=spec.op, short_burn=round(short[0], 4),
                        long_burn=round(long_[0], 4),
                        series=",".join(spec._num_keys()))
            elif was and (short is None
                          or short[0] < spec.burn_threshold):
                self._firing[spec.name] = False
                transitions.append({"slo": spec.name,
                                    "event": "slo_clear",
                                    "value": None if short is None
                                    else short[1]})
                if self._gauge is not None:
                    self._gauge.set(0.0, slo=spec.name)
                if self.flight is not None:
                    self.flight.record(
                        "slo_clear", slo=spec.name,
                        value=None if short is None else short[1])
        # wedge-style episode re-arm: dump once when the fleet enters a
        # firing episode, re-arm only once everything has cleared
        if any(self._firing.values()):
            if not self._dumped:
                self._dumped = True
                if self.dump_fn is not None:
                    fired = [t for t in transitions
                             if t["event"] == "slo_fire"]
                    self.dump_fn(fired or
                                 [{"slo": n, "event": "slo_fire"}
                                  for n in self.firing()])
        else:
            self._dumped = False
        return transitions

    def _burn(self, spec: SloSpec, window_s: float, now: float):
        """(violated_fraction, last_windowed_value) over the trailing
        `window_s`, or None when no window could be evaluated (no data,
        or — for ratios — no traffic)."""
        if spec.kind == "gauge":
            pts = self.history.points(spec.series, last_s=window_s,
                                      now=now)
            vals = [v for _, v in pts]
        else:
            num: dict = {}
            den: dict = {}
            for k in spec._num_keys():
                for t, v in self.history.points(k, last_s=window_s,
                                                now=now):
                    num[t] = num.get(t, 0.0) + v
            for k in spec._den_keys():
                for t, v in self.history.points(k, last_s=window_s,
                                                now=now):
                    den[t] = den.get(t, 0.0) + v
            vals = [num.get(t, 0.0) / d
                    for t, d in sorted(den.items()) if d > 0]
        if not vals:
            return None
        if spec.op == ">":
            bad = sum(1 for v in vals if v > spec.objective)
        else:
            bad = sum(1 for v in vals if v < spec.objective)
        return bad / len(vals), vals[-1]


# -- default objectives ------------------------------------------------------
# Thresholds are deliberately loose operational defaults: they page on a
# collapse, not on noise.  Deployments tune them via the server
# constructors' `slo_specs=` (pass () to disable alerting entirely).

def default_serving_slos() -> list:
    q = 'serving_latency_seconds{quantile="p99",stat="%s"}'
    return [
        SloSpec(name="serving_ttft_p99", series=q % "first_token_latency",
                objective=2.0, op=">",
                description="p99 time-to-first-token under 2s"),
        SloSpec(name="serving_itl_p99", series=q % "token_latency",
                objective=0.5, op=">",
                description="p99 inter-token latency under 500ms"),
        SloSpec(name="serving_shed_ratio", kind="ratio",
                series=("serving_overload_total",),
                den=("serving_requests_accepted_total",
                     "serving_overload_total"),
                objective=0.05, op=">",
                description="under 5% of arrivals shed with overload"),
        SloSpec(name="serving_spec_accept", kind="ratio",
                series=("serving_spec_accepted_total",),
                den=("serving_spec_drafted_total",),
                objective=0.2, op="<",
                description="speculative accept rate above 0.2 while "
                            "drafting (idle windows never burn)"),
        SloSpec(name="serving_prefix_hit", kind="ratio",
                series=("serving_prefix_hits_total",),
                den=("serving_prefix_hits_total",
                     "serving_prefix_misses_total"),
                objective=0.05, op="<",
                description="prefix-cache hit rate above 5% while "
                            "admitting (idle windows never burn)"),
    ]


def default_router_slos() -> list:
    return [
        SloSpec(name="fleet_shed_ratio", kind="ratio",
                series=("fleet_sheds_total",),
                den=("fleet_requests_accepted_total",
                     "fleet_sheds_total"),
                objective=0.05, op=">",
                description="under 5% of fleet arrivals shed"),
        SloSpec(name="fleet_replicas_healthy",
                series="fleet_replicas_healthy", objective=1.0, op="<",
                description="at least one healthy replica registered"),
    ]


def default_pserver_slos() -> list:
    return [
        SloSpec(name="pserver_window_skew", kind="hist_mean",
                series="pserver_window_skew_ms", objective=1000.0,
                op=">",
                description="mean per-window barrier-arrival skew "
                            "under 1s (straggler alarm)"),
    ]
