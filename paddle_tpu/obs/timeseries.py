"""In-memory metric time-series: rolling windows for the health plane.

The metrics registry (obs/metrics.py) answers "what is the value NOW";
the flight recorder answers "what happened around death".  This module
holds the in-between: a bounded, downsampled ring of samples per
catalogued metric series, fed by a background sampler that walks the
registry, so a p99 TTFT blowup, a spec accept-rate collapse, or a
prefix-hit-rate regression has a *history* that an operator
(`tools/obs_top.py`), the SLO evaluator (`obs/slo.py`), and postmortem
bundles can read back.

Storage model, per series key (the registry `snapshot()` flat-dict key
shape — `name` or `name{k="v",...}`):

  * gauges store the LAST value seen in each resolution window;
  * counters store the DELTA against the previous raw reading, clamped
    at >= 0 (a process restart resets to a fresh baseline, never a
    negative spike) — so rates come free: `value / resolution_s`;
  * histograms ride their `_sum`/`_count` samples as counter deltas
    (per-window mean = dsum/dcount); per-bucket series are skipped to
    bound cardinality, and latency *quantiles* already arrive as
    StatSet quantile GAUGES (`statset_collector`), which downsample
    like any other gauge.

Threading follows the metrics/trace discipline: `sample()` runs on a
background `HistorySampler` thread (or a test's manual clock) and reads
only lock-guarded / GIL-atomic registry state — it never round-trips
the pump.  `snapshot()`/`points()` run on the asyncio loop thread
answering the `history` RPC, so the RPC is stale-ok by construction and
answers against a wedged pump; the staleness is visible as
`last_sample_unix`.  Stdlib-only, like the rest of `obs/`.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from typing import Iterable, Optional

from paddle_tpu.obs.metrics import _fmt_labels
from paddle_tpu.obs.trace import process_info

#: distinct series keys the ring refuses past this point — a label
#: explosion must degrade accounting (obs_history_dropped_series_total),
#: never memory
MAX_SERIES = 4096


class MetricHistory:
    """Bounded downsampled ring per metric series."""

    def __init__(self, registry=None, resolution_s: float = 5.0,
                 retention_s: float = 1800.0,
                 max_series: int = MAX_SERIES):
        if resolution_s <= 0 or retention_s <= 0:
            raise ValueError("resolution_s and retention_s must be > 0")
        self.registry = registry
        self.resolution_s = float(resolution_s)
        self.retention_s = float(retention_s)
        #: ring slots per series = retention / resolution
        self.capacity = max(2, int(round(self.retention_s
                                         / self.resolution_s)))
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        # key -> {"kind": "counter"|"gauge",
        #         "ring": deque[(window_index, value)]}, oldest first
        self._series: dict[str, dict] = {}
        self._prev_raw: dict[str, float] = {}   # counters: last raw value
        self.samples_taken = 0
        self.dropped_series = 0
        self.first_sample_unix = 0.0
        self.last_sample_unix = 0.0

    # -- writing (sampler thread / test clock) ----------------------------
    def sample(self, now: Optional[float] = None, samples=None) -> None:
        """Take one downsampling pass.  `samples` overrides the registry
        walk with explicit (name, kind, labels|None, value) tuples
        (tests); `now` overrides the wall clock (deterministic window
        alignment)."""
        if samples is None:
            if self.registry is None:
                raise ValueError("no registry bound and no samples given")
            samples = self.registry.samples()
        now = time.time() if now is None else float(now)
        win = int(now // self.resolution_s)
        with self._lock:
            for name, kind, labels, value in samples:
                if kind == "histogram" and name.endswith("_bucket"):
                    continue                     # cardinality guard
                key = name + _fmt_labels(labels)
                as_counter = kind in ("counter", "histogram")
                ser = self._series.get(key)
                if ser is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    ser = self._series[key] = {
                        "kind": "counter" if as_counter else "gauge",
                        "ring": collections.deque(maxlen=self.capacity)}
                ring = ser["ring"]
                if as_counter:
                    # counters start at 0 in a fresh process, so the
                    # first reading IS the delta since process start
                    prev = self._prev_raw.get(key, 0.0)
                    delta = max(0.0, float(value) - prev)
                    self._prev_raw[key] = float(value)
                    if ring and ring[-1][0] == win:
                        ring[-1] = (win, ring[-1][1] + delta)
                    else:
                        ring.append((win, delta))
                else:
                    v = float(value)
                    if ring and ring[-1][0] == win:
                        ring[-1] = (win, v)
                    else:
                        ring.append((win, v))
            self.samples_taken += 1
            if self.first_sample_unix == 0.0:
                self.first_sample_unix = now
            self.last_sample_unix = now

    # -- reading (any thread; the history RPC's loop-thread path) ---------
    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, key: str) -> Optional[str]:
        with self._lock:
            ser = self._series.get(key)
            return ser["kind"] if ser else None

    def points(self, key: str, last_s: Optional[float] = None,
               now: Optional[float] = None) -> list[tuple]:
        """[(window_start_unix, value)] oldest first, optionally limited
        to the trailing `last_s` seconds."""
        now = time.time() if now is None else float(now)
        with self._lock:
            ser = self._series.get(key)
            pts = list(ser["ring"]) if ser else []
        lo = None if last_s is None else \
            int((now - float(last_s)) // self.resolution_s)
        return [(w * self.resolution_s, v) for w, v in pts
                if lo is None or w >= lo]

    def snapshot(self, last_s: Optional[float] = None,
                 names: Optional[Iterable[str]] = None,
                 now: Optional[float] = None) -> dict:
        """The `history` frame body (and the bundle's history.json):
        top-level ring accounting plus {key: {"kind", "points"}} with
        points as [window_start_unix, value] pairs, oldest first.
        `names` filters series by key prefix; `last_s` trims each series
        to the trailing window."""
        now = time.time() if now is None else float(now)
        pref = tuple(names) if names else None
        with self._lock:
            items = [(k, s["kind"], list(s["ring"]))
                     for k, s in sorted(self._series.items())
                     if pref is None or k.startswith(pref)]
            taken = self.samples_taken
            first = self.first_sample_unix
            last = self.last_sample_unix
            dropped = self.dropped_series
        lo = None if last_s is None else \
            int((now - float(last_s)) // self.resolution_s)
        series = {}
        for k, kind, pts in items:
            out = [[w * self.resolution_s, float(f"{v:.6g}")]
                   for w, v in pts if lo is None or w >= lo]
            if out:
                series[k] = {"kind": kind, "points": out}
        return {"resolution_s": self.resolution_s,
                "retention_s": self.retention_s,
                "samples_taken": taken,
                "first_sample_unix": first,
                "last_sample_unix": last,
                "dropped_series": dropped,
                "series": series}


class HistorySampler:
    """Background thread: one `sample()` per period, plus an optional
    post-sample hook (the SLO evaluator rides it).  `enabled` is a live
    flip — bench_serving's overhead probe toggles it mid-run to price
    the sampler against the decode hot path.  A collector that raises
    must never kill the health plane: errors are counted and the thread
    keeps ticking."""

    def __init__(self, history: MetricHistory,
                 period_s: Optional[float] = None, on_sample=None):
        self.history = history
        self.period_s = float(period_s) if period_s \
            else history.resolution_s
        self.on_sample = on_sample
        self.enabled = True
        self.errors = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="history-sampler",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            if not self.enabled:
                continue
            try:
                self.history.sample()
                if self.on_sample is not None:
                    self.on_sample()
            except Exception as e:     # noqa: BLE001 — the health plane
                self.errors += 1       # must outlive collector bugs
                self.last_error = f"{type(e).__name__}: {e}"


def history_collector(history: MetricHistory):
    """obs.metrics collector: the ring's own accounting (which the
    sampler then records into the ring like any other series)."""

    def collect():
        age = -1.0 if history.last_sample_unix == 0.0 else \
            max(0.0, time.time() - history.last_sample_unix)
        return [
            ("obs_history_series", "gauge", None,
             float(history.series_count())),
            ("obs_history_samples_total", "counter", None,
             float(history.samples_taken)),
            ("obs_history_sample_age_s", "gauge", None, age),
            ("obs_history_dropped_series_total", "counter", None,
             float(history.dropped_series)),
        ]

    return collect


def history_reply(history: MetricHistory, msg: dict, role: str,
                  host=None, port=None, **ident) -> dict:
    """Answer a `history` RPC frame — mirrors obs.trace.trace_reply:
    runs on the asyncio loop thread, reads only lock-guarded ring state,
    and therefore answers while the pump is wedged (stale-ok by
    construction)."""
    proc = process_info(role, host, port)
    proc.update(ident)
    out = {"type": "history", "id": msg.get("id"), "process": proc}
    out.update(history.snapshot(last_s=msg.get("last_s"),
                                names=msg.get("names")))
    return out


# -- fleet aggregation (the router's per-replica merge) ---------------------

_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def relabel_series_key(key: str, extra: dict) -> str:
    """Inject labels into a snapshot()-shaped series key, preserving the
    sorted-label formatting of obs.metrics._fmt_labels — e.g.
    `a{x="1"}` + {replica: "r0"} -> `a{replica="r0",x="1"}`."""
    name, _, inner = key.partition("{")
    labels = {m.group(1): re.sub(r"\\(.)", r"\1", m.group(2))
              for m in _LABEL_RE.finditer(inner)}
    labels.update({k: str(v) for k, v in extra.items()})
    return name + _fmt_labels(labels)


def merge_history(parts, label: str = "replica") -> dict:
    """Merge per-process `history` bodies into one reply body, tagging
    each labeled part's series with `label="<value>"` — the history
    analog of the router's _merge_prometheus metrics merge (PR 13).
    `parts` is [(label_value_or_None, body_dict)]; the None part (the
    router's own series) passes through unlabeled and supplies the
    top-level ring accounting."""
    out: dict = {"series": {}, "replicas": []}
    for value, body in parts:
        if not body:
            continue
        if value is None:
            for k in ("resolution_s", "retention_s", "samples_taken",
                      "first_sample_unix", "last_sample_unix",
                      "dropped_series"):
                if k in body:
                    out[k] = body[k]
            out["series"].update(body.get("series", {}))
        else:
            out["replicas"].append(value)
            for k, s in body.get("series", {}).items():
                out["series"][relabel_series_key(k, {label: value})] = s
    out["replicas"].sort()
    return out
