"""Span tracer: request-lifecycle and trainer-phase timing spans.

The TensorFlow-timeline analog for this stack (arXiv:1605.08695 ships
timeline tracing as a first-class subsystem; the TPU serving literature
diagnoses tail latency via per-phase request spans, arXiv:2605.25645):
lightweight begin/end spans with attributes, recorded into a BOUNDED ring
by the one thread that owns the instrumented state — the serving pump or
the trainer loop — so recording needs no locks and a week-old process
holds the last `capacity` spans, not its lifetime.

Design constraints, in order:

  1. **Off means off.**  `tracer.enabled` is False by default and every
     recording entry point checks it first — a disabled tracer costs one
     attribute read per call site (the bench_serving overhead budget is
     <= 2% with tracing off).
  2. **Single-writer ring.**  Spans are appended by the owning thread
     only; `snapshot()` may run on another thread (drain, a test) and
     copies the list under the GIL, using each record's monotonic `seq`
     to restore order.  No cross-thread mutation, matching the serving
     command-queue architecture.
  3. **Two export shapes.**  Structured JSONL (one span per line — the
     greppable archival form) and Chrome `trace_event` JSON (the
     `tools/trace_dump.py` product, loadable in Perfetto/chrome://tracing).

Span model: a span is (seq, name, track, ts, dur, attrs).  `track` is the
horizontal lane the viewer shows — one per request (`req:<id>`), one for
the engine (`engine`), one for the trainer (`trainer`).  `dur` 0.0 with
`instant=True` renders as an instant marker (preempt, done).  Times are
`time.perf_counter()` seconds; exports convert to microseconds.

Distributed tracing (docs/observability.md "Distributed tracing"): a
request that crosses processes (client → fleet router → replica) carries
a wire-level trace context — `trace_id` (one per request, minted at the
router's ingress unless the client supplied one) and `parent` (the
sending side's span id) — which every process records as span ATTRS, so
stitching needs no tracer-core change.  `merge_chrome()` stitches span
sets pulled from several processes (the `trace` RPC, or `--trace-out`
files) into ONE Chrome trace with a named process group per source,
applying each source's clock offset (perf_counter epochs are
per-process; the puller measures the offset via ping-RTT midpointing).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time
from typing import Optional


def new_trace_id() -> str:
    """One id per cross-process request — 16 hex chars, collision-safe at
    fleet request rates (os.urandom, no seeding to leak)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """Parent-pointer currency for cross-process span stitching."""
    return os.urandom(4).hex()


def process_info(role: str, host: Optional[str] = None,
                 port: Optional[int] = None) -> dict:
    """The process-identity stamp a `trace` RPC reply (and a --trace-out
    file's meta line) carries, so a merged trace can name its tracks:
    role (replica/router/...), pid, hostname, and the bind address."""
    out = {"role": role, "pid": os.getpid(),
           "hostname": socket.gethostname()}
    if host is not None:
        out["addr"] = f"{host}:{port}"
    return out


def trace_reply(tracer: "Tracer", msg: dict, role: str,
                host: Optional[str] = None, port: Optional[int] = None,
                **ident) -> dict:
    """The `trace` RPC reply shared by the serving replica, the fleet
    router, and the pserver shard — trace_dump --pull depends on the
    three agreeing.  Applies a live `enable` flip BEFORE the snapshot
    (so enable:false returns the spans it just froze), stamps process
    identity (extra keyword fields like shard= ride along) plus a
    perf_counter/unix clock sample for ping-RTT alignment, and ships
    the retained ring with its accounting."""
    if isinstance(msg.get("enable"), bool):
        tracer.enabled = msg["enable"]
    proc = process_info(role, host, port)
    proc.update(ident)
    return {"type": "trace", "id": msg.get("id"),
            "process": proc,
            "clock": {"perf_counter": time.perf_counter(),
                      "unix": time.time()},
            "enabled": tracer.enabled,
            "recorded": tracer.recorded,
            "dropped": tracer.dropped,
            "spans": tracer.snapshot()}


def flush_trace_file(tracer: "Tracer", path: str, role: str,
                     host: Optional[str] = None,
                     port: Optional[int] = None, **ident) -> int:
    """Write `tracer`'s retained ring to `path` as JSONL with the
    leading `{"meta": {"process": ...}}` identity line, and note the
    count on stderr — the flush-on-every-exit-path discipline shared by
    serve.py, fleet_router.py, pserver.py, and train_dist.py.  Extra
    keyword fields (rank=, shard=) ride in the identity record so
    trace_dump --merge can name the track."""
    proc = process_info(role, host, port)
    proc.update(ident)
    n = tracer.export_jsonl(path, meta={"process": proc})
    print(f"wrote {n} spans to {path} ({tracer.dropped} dropped by "
          f"ring wrap); stitch with tools/trace_dump.py --merge",
          file=sys.stderr, flush=True)
    return n


class _NullSpan:
    """Shared no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    """Context manager recording one complete span on exit."""

    __slots__ = ("tracer", "name", "track", "attrs", "t0")

    def __init__(self, tracer, name, track, attrs):
        self.tracer = tracer
        self.name = name
        self.track = track
        self.attrs = attrs

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.tracer.add(self.name, self.t0,
                        time.perf_counter() - self.t0,
                        track=self.track, attrs=self.attrs)
        return False


class Tracer:
    """Bounded-ring span recorder.  One writer thread; see module note."""

    def __init__(self, capacity: int = 16384):
        self.capacity = int(capacity)
        assert self.capacity > 0
        self.enabled = False
        self._ring: list = []          # grows to capacity, then wraps
        self._n = 0                    # spans ever recorded (monotonic)

    # -- recording (owner thread) -----------------------------------------
    def add(self, name: str, ts: float, dur: float, track: str = "main",
            attrs: Optional[dict] = None, instant: bool = False) -> None:
        """Record one completed span (ts/dur in perf_counter seconds).

        Designed single-writer (the pump/trainer thread).  An occasional
        add from a sibling thread (the trainer's h2d prefetch lane) is
        GIL-safe — list ops never tear — but a racing pair may overwrite
        one span; tracing tolerates a lost sample, so no lock is paid on
        the per-step hot path."""
        if not self.enabled:
            return
        rec = (self._n, name, track, ts, dur, attrs,
               True if instant else False)
        if len(self._ring) < self.capacity:
            self._ring.append(rec)
        else:
            self._ring[self._n % self.capacity] = rec
        self._n += 1

    def span(self, name: str, track: str = "main", **attrs):
        """``with tracer.span("prefill", bucket=32): ...`` — records on
        exit; a shared no-op object when disabled."""
        if not self.enabled:
            return _NULL
        return _Span(self, name, track, attrs or None)

    def begin(self, name: str, track: str = "main", **attrs):
        """Open a span that a LATER call (possibly in another method)
        closes via end().  Returns an opaque handle; None when disabled —
        end(None) is a no-op, so call sites never branch."""
        if not self.enabled:
            return None
        return [name, track, time.perf_counter(), attrs or None]

    def end(self, handle, **extra_attrs) -> None:
        if handle is None:
            return
        name, track, t0, attrs = handle
        if extra_attrs:
            attrs = dict(attrs or (), **extra_attrs)
        self.add(name, t0, time.perf_counter() - t0, track=track,
                 attrs=attrs)

    def instant(self, name: str, track: str = "main", **attrs) -> None:
        """Zero-duration marker (preempt, done, cancelled)."""
        if not self.enabled:
            return
        self.add(name, time.perf_counter(), 0.0, track=track,
                 attrs=attrs or None, instant=True)

    # -- reading / export (any thread) ------------------------------------
    @property
    def recorded(self) -> int:
        """Spans ever recorded (monotonic, includes overwritten)."""
        return self._n

    @property
    def dropped(self) -> int:
        """Spans overwritten by ring wrap-around."""
        return max(0, self._n - self.capacity)

    def clear(self) -> None:
        self._ring = []
        self._n = 0

    def snapshot(self) -> list[dict]:
        """Retained spans, oldest first, as dicts — the JSONL record
        shape.  Copies under the GIL; safe concurrent with recording
        (a span landing mid-copy may or may not appear, never torn)."""
        recs = sorted(list(self._ring))          # seq-first tuples
        return [{"seq": r[0], "name": r[1], "track": r[2],
                 "ts": r[3], "dur": r[4],
                 **({"attrs": r[5]} if r[5] else {}),
                 **({"instant": True} if r[6] else {})}
                for r in recs]

    def export_jsonl(self, path: str, meta: Optional[dict] = None) -> int:
        """Write retained spans as JSON-lines; returns the span count.
        `meta` (e.g. {"process": process_info(...)}) prepends one
        identity record — tools/trace_dump.py skips it when summarizing
        and uses it to label the process track when merging."""
        spans = self.snapshot()
        with open(path, "w") as f:
            if meta:
                f.write(json.dumps({"meta": meta},
                                   separators=(",", ":")) + "\n")
            for s in spans:
                f.write(json.dumps(s, separators=(",", ":")) + "\n")
        return len(spans)

    def chrome_trace(self) -> dict:
        """Chrome trace_event JSON object (Perfetto-loadable)."""
        return spans_to_chrome(self.snapshot())

    def export_chrome(self, path: str) -> int:
        spans = self.snapshot()
        with open(path, "w") as f:
            json.dump(spans_to_chrome(spans), f)
        return len(spans)


def spans_to_chrome(spans: list[dict]) -> dict:
    """JSONL-shaped span records -> Chrome trace_event JSON.

    Each track becomes a tid with a thread_name metadata event; complete
    spans are "X" events, instants are "i" (thread-scoped).  Times convert
    from perf_counter seconds to integer-friendly microseconds, rebased to
    the earliest span so the viewer opens at t=0."""
    events = _chrome_events(spans, pid=os.getpid(),
                            t_base=min((s["ts"] for s in spans),
                                       default=0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _chrome_events(spans: list[dict], pid: int, t_base: float,
                   offset_s: float = 0.0,
                   process_name: Optional[str] = None) -> list[dict]:
    """One source's spans as Chrome events under process `pid`, with its
    clock offset applied (local = source ts + offset) and all times
    rebased to `t_base` (already in the merged/local timebase)."""
    tids: dict[str, int] = {}
    events: list[dict] = []
    if process_name:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": process_name}})
    for s in spans:
        track = s.get("track", "main")
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": track}})
        ev = {"name": s["name"], "pid": pid, "tid": tid,
              "ts": round((s["ts"] + offset_s - t_base) * 1e6, 3),
              "cat": track.split(":", 1)[0]}
        if s.get("attrs"):
            ev["args"] = s["attrs"]
        if s.get("instant"):
            ev["ph"] = "i"
            ev["s"] = "t"
        else:
            ev["ph"] = "X"
            ev["dur"] = round(s["dur"] * 1e6, 3)
        events.append(ev)
    return events


def merge_chrome(sources: list[dict]) -> dict:
    """Stitch span sets from SEVERAL processes into one Chrome trace.

    Each source is {"spans": [...], "process": {...}|None,
    "offset_s": float} — spans in that process's perf_counter timebase,
    `offset_s` mapping them onto the merger's timebase (local ≈ remote +
    offset; 0.0 for local files).  Every source becomes its own process
    track group (synthetic pids — two replicas on one host, or an
    in-process test fleet, must not collapse into one group), named from
    its process identity; all events rebase to the earliest aligned span
    so the merged trace opens at t=0 with the processes side by side."""
    t_base = min((s["ts"] + src.get("offset_s", 0.0)
                  for src in sources for s in src.get("spans", ())),
                 default=0.0)
    events: list[dict] = []
    for i, src in enumerate(sources):
        proc = src.get("process") or {}
        name = " ".join(
            str(x) for x in (proc.get("role"), proc.get("addr"),
                             f"pid={proc['pid']}" if "pid" in proc
                             else None, src.get("label"))
            if x) or f"process-{i + 1}"
        events.extend(_chrome_events(
            src.get("spans", []), pid=i + 1, t_base=t_base,
            offset_s=float(src.get("offset_s", 0.0)), process_name=name))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: the process-global tracer every subsystem records into by default —
#: serving engine spans, trainer barrier windows, pass/eval spans.  Off
#: until something (tools/serve.py --trace-out, bench.py's overhead probe,
#: a test) flips `.enabled`.
_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer
