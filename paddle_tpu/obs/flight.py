"""Flight recorder: bounded structured-event ring + atomic postmortem bundles.

PR 5's telemetry (spans, metrics frame, watchdog) is all in-memory — when a
replica actually dies or wedges, everything dies with it and the operator
gets a stack trace at best.  The flight recorder is the black box: a small
BOUNDED ring of structured events (engine state transitions, admission and
overload decisions, compile events, sampled watchdog beats) that costs
nothing on the token hot path (events are per-request, per-compile, and
per-second — never per-token), and a `dump()` that freezes everything an
operator needs into one atomic on-disk **postmortem bundle**:

    <dir>/postmortem-<utc-ts>-<pid>/
        meta.json      reason, timestamps, pid/host, component versions
        events.jsonl   the flight-recorder ring, oldest first
        spans.jsonl    the span tracer's retained ring (obs/trace.py shape —
                       tools/trace_dump.py loads it directly)
        engine.json    serving snapshot: slots, page occupancy, queued ids
        metrics.json   flat registry snapshot (obs/metrics.py shape)
        config.json    the serving configuration that produced the crash

The bundle directory is staged under a `.tmp` suffix and committed with one
`os.replace`, mirroring the trainer's atomic checkpoints — a crash mid-dump
leaves a visible `.tmp` straggler, never a half-readable bundle.
`tools/postmortem.py` pretty-prints one; `load_bundle()` is the programmatic
reader both it and the tests use.

The serving front end (serving/server.py) triggers dumps on pump death,
on the watchdog-wedge threshold, and on an operator `dump` RPC frame; the
engine and server record lifecycle events whenever `enabled` is on.  Like
the tracer, this module is stdlib-only (client-side tools import it
without jax).

Threading: events arrive from the pump thread AND the asyncio loop thread
(accept/overload vs admit/preempt), so `record` takes a lock — acceptable
because events are orders of magnitude rarer than tokens.  `dump()` may run
on any thread; it reads rings via their snapshot paths.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from typing import Optional

#: bundle directory prefix — tests and tools key off it
BUNDLE_PREFIX = "postmortem-"

#: files every bundle carries (engine/config may hold {} for non-serving
#: dumps, but the file is always present so readers never stat-and-branch)
BUNDLE_FILES = ("meta.json", "events.jsonl", "spans.jsonl", "engine.json",
                "metrics.json", "config.json")


class FlightRecorder:
    """Bounded ring of structured events; off until `enabled` is set."""

    def __init__(self, capacity: int = 2048):
        self.capacity = int(capacity)
        assert self.capacity > 0
        self.enabled = False
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()   # serializes whole bundles
        self._ring: list = []          # grows to capacity, then wraps
        self._n = 0                    # events ever recorded (monotonic)
        self.bundles_written = 0
        self.last_bundle_path: Optional[str] = None

    # -- recording (any thread) -------------------------------------------
    def record(self, kind: str, **data) -> None:
        """Append one event.  `data` must be JSON-serializable; keep it
        small (ids and counts, not payloads)."""
        if not self.enabled:
            return
        with self._lock:
            rec = (self._n, time.time(), kind, data or None)
            if len(self._ring) < self.capacity:
                self._ring.append(rec)
            else:
                self._ring[self._n % self.capacity] = rec
            self._n += 1

    # -- reading -----------------------------------------------------------
    @property
    def recorded(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._n = 0

    def snapshot(self) -> list[dict]:
        """Retained events, oldest first, as dicts (the events.jsonl
        record shape)."""
        with self._lock:
            recs = sorted(self._ring)
        return [{"seq": r[0], "ts": r[1], "kind": r[2],
                 **({"data": r[3]} if r[3] else {})} for r in recs]

    # -- the postmortem bundle --------------------------------------------
    def dump(self, out_dir: str, reason: str, *, spans=None, engine=None,
             metrics=None, config=None, history=None,
             error: Optional[str] = None) -> str:
        """Write one atomic postmortem bundle under `out_dir`; returns the
        committed bundle path.  Never raises into a dying caller's frame
        for snapshot problems — a part that fails to serialize is replaced
        by an {"snapshot_error": ...} stub (the bundle must outlive the
        bug it documents); only out_dir-level I/O errors propagate.

        Serialized: concurrent dumps (a pump-death dump racing an
        operator `dump` RPC from the loop thread) each commit their OWN
        complete bundle instead of interleaving writes into a shared
        same-second staging dir."""
        with self._dump_lock:
            return self._dump_locked(out_dir, reason, spans=spans,
                                     engine=engine, metrics=metrics,
                                     config=config, history=history,
                                     error=error)

    def _dump_locked(self, out_dir: str, reason: str, *, spans=None,
                     engine=None, metrics=None, config=None, history=None,
                     error: Optional[str] = None) -> str:
        ts = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        base = os.path.join(out_dir, f"{BUNDLE_PREFIX}{ts}-{os.getpid()}")
        final = base
        n = 0
        # same-second re-dump: probe the .tmp path too, so a straggler
        # from a crashed earlier dump is never reused as our staging dir
        while os.path.exists(final) or os.path.exists(final + ".tmp"):
            n += 1
            final = f"{base}.{n}"
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)

        def _write_json(name, obj):
            with open(os.path.join(tmp, name), "w") as f:
                try:
                    json.dump(obj, f, indent=2, default=str)
                except (TypeError, ValueError) as e:
                    f.seek(0)
                    f.truncate()
                    json.dump({"snapshot_error": f"{type(e).__name__}: {e}"},
                              f)

        def _write_jsonl(name, records):
            with open(os.path.join(tmp, name), "w") as f:
                for rec in records:
                    try:
                        f.write(json.dumps(rec, separators=(",", ":"),
                                           default=str) + "\n")
                    except (TypeError, ValueError):
                        continue

        meta = {
            "reason": reason,
            "ts": time.time(),
            "ts_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "events_recorded": self.recorded,
            "events_dropped": self.dropped,
            "versions": _versions(),
        }
        if error:
            meta["error"] = error
        _write_json("meta.json", meta)
        _write_jsonl("events.jsonl", self.snapshot())
        _write_jsonl("spans.jsonl", _safe(lambda: spans or [], []))
        _write_json("engine.json", _safe(lambda: engine or {}, {}))
        _write_json("metrics.json", _safe(lambda: metrics or {}, {}))
        _write_json("config.json", _safe(lambda: config or {}, {}))
        # the health plane's trailing window (PR 20) — written by every
        # new dump but deliberately NOT in BUNDLE_FILES, so bundles from
        # before the health plane stay loadable
        _write_json("history.json", _safe(lambda: history or {}, {}))
        os.replace(tmp, final)             # commit: rename is the txn
        self.bundles_written += 1
        self.last_bundle_path = final
        return final


def _safe(fn, fallback):
    try:
        return fn()
    except Exception as e:                 # noqa: BLE001 — see dump()
        return {"snapshot_error": f"{type(e).__name__}: {e}"} \
            if isinstance(fallback, dict) else fallback


def _versions() -> dict:
    out = {"python": sys.version.split()[0]}
    for mod in ("jax", "jaxlib", "numpy"):
        try:
            out[mod] = __import__(mod).__version__
        except Exception:                  # noqa: BLE001 — absent is fine
            pass
    return out


def load_bundle(path: str) -> dict:
    """Read a committed bundle back: {"path", "meta", "events", "spans",
    "engine", "metrics", "config"}.  Raises ValueError on a directory that
    is not a complete bundle (e.g. a crashed dump's `.tmp` straggler)."""
    if not os.path.isdir(path):
        raise ValueError(f"{path}: not a bundle directory")
    missing = [f for f in BUNDLE_FILES
               if not os.path.exists(os.path.join(path, f))]
    if missing:
        raise ValueError(f"{path}: incomplete bundle, missing {missing} "
                         f"(a .tmp straggler from a crashed dump?)")
    out = {"path": path}
    for name in ("meta", "engine", "metrics", "config"):
        with open(os.path.join(path, name + ".json")) as f:
            out[name] = json.load(f)
    for name in ("events", "spans"):
        recs = []
        with open(os.path.join(path, name + ".jsonl")) as f:
            for line in f:
                line = line.strip()
                if line:
                    recs.append(json.loads(line))
        out[name] = recs
    # optional part: the history ring snapshot (absent in pre-PR-20
    # bundles — readers branch on the key, never fail the load)
    hpath = os.path.join(path, "history.json")
    if os.path.exists(hpath):
        with open(hpath) as f:
            out["history"] = json.load(f)
    return out


def flight_collector(recorder: "FlightRecorder"):
    """obs.metrics collector: ring accounting + bundles written."""

    def collect():
        return [
            ("flight_events_recorded_total", "counter", None,
             float(recorder.recorded)),
            ("flight_events_dropped_total", "counter", None,
             float(recorder.dropped)),
            ("postmortem_bundles_total", "counter", None,
             float(recorder.bundles_written)),
        ]

    return collect


#: the process-global recorder every subsystem records into — the serving
#: engine and front end share it so one bundle holds the whole story.  Off
#: until a ServingServer (or a test/tool) flips `.enabled`.
_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _recorder
