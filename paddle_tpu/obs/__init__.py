"""Unified observability: span tracing, metrics, pump watchdog.

Three pieces (stdlib-only — importable from the client-side tools and the
dependency-light serving client path without pulling in jax):

  * `obs.trace` — a bounded-ring span tracer (request lifecycle on the
    serving pump, per-dispatch phases on the trainer), exportable as
    structured JSONL and Chrome `trace_event` JSON (Perfetto-loadable;
    `tools/trace_dump.py`).  `get_tracer()` is the process-global
    instance, disabled by default.
  * `obs.metrics` — a registry of counters/gauges/histograms with labels
    that unifies StatSet, BarrierTimer, and the serving engine's counters
    behind one Prometheus-style `render()` (the server's `metrics` frame)
    and a flat `snapshot()` (the trainer's `metrics.jsonl` sink).
    `CATALOG` pins every metric name; `tools/check_metrics_names.py`
    keeps it in lockstep with `docs/observability.md`.
  * the pump heartbeat watchdog lives with its thread in
    `serving/server.py` and exports through this registry
    (`pump_last_step_age_s`, `pump_alive`).
  * `obs.compile_watch` — per-signature jit compile events on a `compile`
    tracer lane with a recompile-storm detector (`get_compile_watch()`,
    always on — compiles are rare).
  * `obs.hbm` — device-memory accounting (KV pool / param / live-array
    bytes plus the backend's own stats, CPU-safe).
  * `obs.flight` — the flight recorder: a bounded structured-event ring
    that dumps atomic postmortem bundles on pump death / watchdog wedge /
    an operator `dump` RPC (`get_flight_recorder()`;
    `tools/postmortem.py` pretty-prints a bundle).
  * `obs.timeseries` — the health plane's storage: a bounded in-memory
    ring of downsampled samples per catalogued metric (counters as
    deltas, gauges as last-value), fed by a background `HistorySampler`
    and served over the `history` RPC (`tools/obs_top.py` renders it
    live).
  * `obs.slo` — declarative SLO specs + multi-window burn-rate alerting
    over the time-series; firing transitions emit `slo_fire`/`slo_clear`
    flight events, flip `obs_slo_firing`, and freeze one proactive
    postmortem bundle per episode.

See docs/observability.md for the span model, metric reference, the
trace_dump workflow, and the postmortem-bundle format.
"""

from paddle_tpu.obs.compile_watch import (CompileWatch,  # noqa: F401
                                          compile_collector,
                                          get_compile_watch)
from paddle_tpu.obs.flight import (FlightRecorder,  # noqa: F401
                                   flight_collector, get_flight_recorder,
                                   load_bundle)
from paddle_tpu.obs.hbm import hbm_collector, hbm_snapshot  # noqa: F401
from paddle_tpu.obs.metrics import (CATALOG, Counter,  # noqa: F401
                                    Gauge, Histogram, MetricsRegistry,
                                    barrier_collector, statset_collector,
                                    tracer_collector)
from paddle_tpu.obs.slo import (SloEvaluator, SloSpec,  # noqa: F401
                                default_pserver_slos, default_router_slos,
                                default_serving_slos)
from paddle_tpu.obs.timeseries import (HistorySampler,  # noqa: F401
                                       MetricHistory, history_collector,
                                       history_reply, merge_history,
                                       relabel_series_key)
from paddle_tpu.obs.trace import (Tracer, flush_trace_file,  # noqa: F401
                                  get_tracer, merge_chrome, new_span_id,
                                  new_trace_id, process_info,
                                  spans_to_chrome)

__all__ = ["Tracer", "get_tracer", "spans_to_chrome", "merge_chrome",
           "flush_trace_file",
           "new_trace_id", "new_span_id", "process_info", "MetricsRegistry",
           "Counter", "Gauge", "Histogram", "CATALOG", "statset_collector",
           "barrier_collector", "tracer_collector", "CompileWatch",
           "get_compile_watch", "compile_collector", "FlightRecorder",
           "get_flight_recorder", "flight_collector", "load_bundle",
           "hbm_collector", "hbm_snapshot", "MetricHistory",
           "HistorySampler", "history_collector", "history_reply",
           "merge_history", "relabel_series_key", "SloSpec",
           "SloEvaluator", "default_serving_slos", "default_router_slos",
           "default_pserver_slos"]
