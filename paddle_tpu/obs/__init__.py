"""Unified observability: span tracing, metrics, pump watchdog.

Three pieces (stdlib-only — importable from the client-side tools and the
dependency-light serving client path without pulling in jax):

  * `obs.trace` — a bounded-ring span tracer (request lifecycle on the
    serving pump, per-dispatch phases on the trainer), exportable as
    structured JSONL and Chrome `trace_event` JSON (Perfetto-loadable;
    `tools/trace_dump.py`).  `get_tracer()` is the process-global
    instance, disabled by default.
  * `obs.metrics` — a registry of counters/gauges/histograms with labels
    that unifies StatSet, BarrierTimer, and the serving engine's counters
    behind one Prometheus-style `render()` (the server's `metrics` frame)
    and a flat `snapshot()` (the trainer's `metrics.jsonl` sink).
    `CATALOG` pins every metric name; `tools/check_metrics_names.py`
    keeps it in lockstep with `docs/observability.md`.
  * the pump heartbeat watchdog lives with its thread in
    `serving/server.py` and exports through this registry
    (`pump_last_step_age_s`, `pump_alive`).

See docs/observability.md for the span model, metric reference, and the
trace_dump workflow.
"""

from paddle_tpu.obs.metrics import (CATALOG, Counter,  # noqa: F401
                                    Gauge, Histogram, MetricsRegistry,
                                    barrier_collector, statset_collector,
                                    tracer_collector)
from paddle_tpu.obs.trace import (Tracer, get_tracer,  # noqa: F401
                                  spans_to_chrome)

__all__ = ["Tracer", "get_tracer", "spans_to_chrome", "MetricsRegistry",
           "Counter", "Gauge", "Histogram", "CATALOG", "statset_collector",
           "barrier_collector", "tracer_collector"]
