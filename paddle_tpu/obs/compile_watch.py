"""Compile observability: per-signature jit compile events + storm detector.

On TPU, compile time is a first-class operational signal (the serving
comparisons in arXiv:2605.25645 treat it on par with throughput): a decode
step that stays at ONE signature is the whole point of the slot engine, and
per-bucket prefill means a handful of deliberate compiles — but a workload
that churns buckets (or a shape bug in a new graph path) turns "a handful"
into a RECOMPILE STORM where the chip spends its time in XLA instead of
serving.  Today that is invisible until tokens/sec craters.  This module
makes every compile an event:

  * `wrap_jit(site, fn)` wraps a jitted callable.  After each call it
    checks the jit cache size — growth means THIS call compiled — and
    records: a span on the `compile` tracer lane (name = site, dur =
    compile + first-run wall time, attrs = signature), a flight-recorder
    event, and `jit_compiles_total` / `jit_compile_seconds` /
    `jit_signatures` samples via `compile_collector()`.  The non-compile
    fast path costs two `_cache_size()` reads and two clock reads — noise
    against a real dispatch.  Attribute access proxies to the wrapped fn,
    so `.lower()` / `._cache_size()` introspection (bench.py, the HLO
    checks, the serving signature oracles) keeps working.
  * `watch(site, key)` is the context-manager form for compiled paths that
    are not a single jit object (lm_generate's per-(B,P,max_new) scans):
    the first call with a new `key` records a compile event timed over the
    whole call (trace + compile + first run — the honest measurable).
  * the STORM DETECTOR: >= `storm_n` distinct signatures for one site
    inside `storm_window_s` seconds fires a warning once — a
    `recompile_storm` instant on the compile lane, a flight event, and
    `jit_recompile_storms_total` — then stays quiet until the window
    drains (so a sustained storm is one alert, not an alert storm).

Like the tracer and flight recorder this is a process-global singleton
(`get_compile_watch()`), stdlib-only, and always on: compile events are
rare enough that there is no flag to forget.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque

from paddle_tpu.obs.flight import get_flight_recorder
from paddle_tpu.obs.trace import get_tracer


def signature_of(args: tuple, kwargs: dict) -> str:
    """A stable short signature for a call's abstract shapes: walks the
    args pytree duck-typed (no jax import — this module loads on the
    dependency-light client path), describing array-ish leaves as
    dtype[shape].  Big pytrees (a params dict) hash down to a digest so
    the signature stays log-line sized."""
    parts: list[str] = []

    def walk(x):
        if isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
        elif isinstance(x, dict):
            for k in sorted(x, key=str):
                walk(x[k])
        elif hasattr(x, "shape") and hasattr(x, "dtype"):
            parts.append(f"{x.dtype}[{','.join(map(str, x.shape))}]")
        elif isinstance(x, (bool, int, float, str)) or x is None:
            parts.append(repr(x))
        else:
            parts.append(type(x).__name__)

    walk(args)
    walk(kwargs)
    full = ";".join(parts)
    if len(full) <= 96:
        return full
    digest = hashlib.md5(full.encode()).hexdigest()[:10]
    return f"{len(parts)} leaves:{digest}:{full[:64]}…"


class _Watch:
    """Context manager for watch(): records on exit iff the key was new."""

    __slots__ = ("cw", "site", "key", "t0")

    def __init__(self, cw, site, key):
        self.cw = cw
        self.site = site
        self.key = key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None and self.key is not None:
            self.cw.note(self.site, self.key,
                         time.perf_counter() - self.t0, t0=self.t0)
        return False


class _WatchedJit:
    """Callable proxy over one jitted function (see wrap_jit)."""

    __slots__ = ("_fn", "_site", "_cw")

    def __init__(self, fn, site, cw):
        object.__setattr__(self, "_fn", fn)
        object.__setattr__(self, "_site", site)
        object.__setattr__(self, "_cw", cw)

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            n0 = fn._cache_size()
        except Exception:                  # noqa: BLE001 — no cache probe
            n0 = None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if n0 is not None:
            try:
                compiled = fn._cache_size() > n0
            except Exception:              # noqa: BLE001
                compiled = False
            if compiled:
                self._cw.record(self._site, signature_of(args, kwargs),
                                time.perf_counter() - t0, t0=t0)
        return out

    def __getattr__(self, name):           # .lower(), ._cache_size(), ...
        return getattr(self._fn, name)


class CompileWatch:
    """Per-site compile accounting + the recompile-storm detector."""

    def __init__(self, storm_n: int = 6, storm_window_s: float = 60.0):
        self.storm_n = int(storm_n)
        self.storm_window_s = float(storm_window_s)
        self._lock = threading.Lock()
        self.compiles: dict[str, int] = {}        # site -> compile count
        self.seconds: dict[str, float] = {}       # site -> wall seconds
        self.storms: dict[str, int] = {}          # site -> storms fired
        self._sigs: dict[str, set] = {}           # site -> distinct sigs
        self._recent: dict[str, deque] = {}       # site -> (t, sig) window
        self._armed: dict[str, bool] = {}         # storm re-arm per site

    def clear(self) -> None:
        with self._lock:
            self.compiles.clear()
            self.seconds.clear()
            self.storms.clear()
            self._sigs.clear()
            self._recent.clear()
            self._armed.clear()

    # -- instrumentation entry points --------------------------------------
    def wrap_jit(self, site: str, fn) -> _WatchedJit:
        """Wrap a jitted callable; compile events detected by jit-cache
        growth, so repeat signatures cost no signature computation."""
        return _WatchedJit(fn, site, self)

    def watch(self, site: str, key) -> _Watch:
        """``with cw.watch("lm_decode.generate", (B, P, max_new)): ...`` —
        records a compile event on exit if `key` is new for the site."""
        with self._lock:
            known = key in self._sigs.get(site, ())
        return _Watch(self, site, None if known else key)

    def note(self, site: str, key, seconds: float, t0: float = 0.0) -> None:
        """Record a first-call-for-key event unless the key raced in."""
        with self._lock:
            if key in self._sigs.get(site, ()):
                return
        self.record(site, str(key), seconds, t0=t0, raw_key=key)

    # -- the event ---------------------------------------------------------
    def record(self, site: str, sig: str, seconds: float,
               t0: float = 0.0, raw_key=None) -> None:
        """One compile happened at `site` with signature `sig`, costing
        `seconds` of wall time (compile + first run)."""
        now = time.perf_counter()
        storm = None
        key = raw_key if raw_key is not None else sig
        with self._lock:
            self.compiles[site] = self.compiles.get(site, 0) + 1
            self.seconds[site] = self.seconds.get(site, 0.0) + seconds
            self._sigs.setdefault(site, set()).add(key)
            dq = self._recent.setdefault(site, deque())
            while dq and dq[0][0] < now - self.storm_window_s:
                dq.popleft()
            if not dq:
                self._armed[site] = True   # window drained: re-arm
            dq.append((now, key))
            distinct = len({s for _, s in dq})
            if distinct >= self.storm_n and self._armed.get(site, True):
                self._armed[site] = False
                self.storms[site] = self.storms.get(site, 0) + 1
                storm = distinct
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add(site, t0 or (now - seconds), seconds,
                       track="compile", attrs={"sig": sig})
        flight = get_flight_recorder()
        flight.record("compile", site=site, sig=sig,
                      seconds=round(seconds, 4))
        if storm is not None:
            if tracer.enabled:
                tracer.instant("recompile_storm", track="compile",
                               site=site, signatures=storm,
                               window_s=self.storm_window_s)
            flight.record("recompile_storm", site=site,
                          signatures=storm,
                          window_s=self.storm_window_s)

    # -- reading -----------------------------------------------------------
    def signature_count(self, site: str) -> int:
        with self._lock:
            return len(self._sigs.get(site, ()))

    def snapshot(self) -> dict:
        """{site: {"compiles", "seconds", "signatures", "storms"}} — the
        postmortem-bundle shape."""
        with self._lock:
            sites = set(self.compiles) | set(self._sigs)
            return {site: {
                "compiles": self.compiles.get(site, 0),
                "seconds": round(self.seconds.get(site, 0.0), 4),
                "signatures": len(self._sigs.get(site, ())),
                "storms": self.storms.get(site, 0),
            } for site in sorted(sites)}


def compile_collector(cw: "CompileWatch" = None):
    """obs.metrics collector: per-site compile counters + signature
    gauges.  One collector instance serves both the serving server's and
    the trainer's registries (the watcher is process-global)."""

    def collect():
        w = cw or _watch
        out = []
        for site, st in w.snapshot().items():
            labels = {"site": site}
            out.append(("jit_compiles_total", "counter", labels,
                        float(st["compiles"])))
            out.append(("jit_compile_seconds", "counter", labels,
                        float(st["seconds"])))
            out.append(("jit_signatures", "gauge", labels,
                        float(st["signatures"])))
            out.append(("jit_recompile_storms_total", "counter", labels,
                        float(st["storms"])))
        return out

    return collect


#: process-global watcher — every instrumented jit entry point (trainer
#: train/eval steps, serving decode/prefill/pack, lm_generate) records here
_watch = CompileWatch()


def get_compile_watch() -> CompileWatch:
    return _watch
