"""Metrics registry: counters / gauges / histograms with labels.

One registry unifies the stack's three pre-existing ad-hoc stat systems —
`utils/stat.py` StatSet (host-phase timers + serving latency windows),
`parallel/barrier_stat.py` BarrierTimer (per-step dispatch/sync/h2d/scan
windows), and the serving engine's occupancy/preemption counters — behind
a single render surface:

  * a Prometheus-style text exposition (`render()`), served by the RPC
    front end as the `metrics` frame and one-shotted by
    `tools/serve.py --metrics`;
  * a flat `snapshot()` dict, appended by the trainer to a
    `metrics.jsonl` sink next to its checkpoints.

Existing stat objects are NOT rewritten — they keep their owners and
their thread contracts, and the registry pulls from them at render time
through **collectors** (`register_collector`): a collector is a zero-arg
callable returning `(name, kind, labels|None, value)` samples.  That
keeps render a read-only observer of state the pump/trainer threads own,
consistent with the no-cross-thread-mutation architecture.

`CATALOG` is the authoritative name -> help map for every metric this
repo emits.  A registry built with `strict=True` (the server's and the
trainer's are) refuses metric names outside it, and
`tools/check_metrics_names.py` asserts CATALOG and
`docs/observability.md` agree both ways — so a metric cannot ship
undocumented, and the doc cannot drift from the code.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

#: every metric name this repo emits -> one-line help.  The single source
#: of truth the strict registries and the docs lint both anchor to.
CATALOG: dict[str, str] = {
    # -- serving: engine state (pump-consistent in the stats RPC) ---------
    "serving_queue_depth": "requests waiting in the engine FIFO",
    "serving_slots_in_use": "decode slots holding an in-flight request",
    "serving_num_slots": "configured decode slots",
    "serving_pages_in_use": "KV pages allocated to slots",
    "serving_free_pages": "KV pages on the free list",
    "serving_num_pages": "configured KV page pool size (incl. trash page)",
    "serving_private_pages_in_use":
        "KV pages mapped by exactly one slot and not prefix-cached",
    "serving_shared_pages_in_use":
        "slot-mapped KV pages shared read-only (multi-slot or prefix-cached)",
    "serving_prefix_cached_pages":
        "KV pages retained only by the prefix index (evictable on pressure)",
    "serving_prefix_nodes": "nodes in the radix prefix index",
    "serving_prefix_hits_total":
        "admissions that mapped at least one cached prefix page",
    "serving_prefix_misses_total":
        "admissions that found no cached prefix (prefix cache enabled)",
    "serving_prefix_tokens_saved_total":
        "prompt tokens skipped at prefill via cached prefixes",
    "serving_prefix_evictions_total":
        "prefix pages evicted by page-pool pressure (LRU, before pausing)",
    "serving_prefix_cow_total":
        "copy-on-write page copies (divergence inside a shared boundary page)",
    # -- host KV spill tier (docs/serving.md "KV spill tier") -------------
    "serving_spill_pages_total":
        "cold cached pages spilled to host RAM instead of destroyed",
    "serving_restore_pages_total":
        "spilled pages restored to device on a prefix hit",
    "serving_spill_bytes":
        "host-RAM bytes currently held by the spill tier",
    "serving_decode_steps_total": "compiled decode steps executed",
    # -- cross-replica KV transfer (docs/serving.md "Disaggregated
    # prefill/decode") ----------------------------------------------------
    "serving_kv_xfer_pushes_total":
        "outbound kv_push attempts (prefill_only completions that tried "
        "to ship their committed prefix to a decode replica)",
    "serving_kv_xfer_push_failures_total":
        "outbound kv_push attempts that failed (connect refused, peer "
        "error, timeout, nothing cached) — the router falls back to "
        "colocated placement on each",
    "serving_kv_xfer_pages_shipped_total":
        "committed KV pages serialized to the wire by export_pages",
    "serving_kv_xfer_pages_received_total":
        "KV pages scattered into the pool from inbound kv_push blobs",
    "serving_kv_xfer_mounts_total":
        "inbound blobs mounted read-only into the prefix tree "
        "(import_prefix calls that added at least zero runs)",
    # -- tensor-parallel sharded decode (docs/serving.md "Sharded decode")
    "serving_tp_shards":
        "tensor-parallel shards (mesh model-axis size; 1 = unsharded)",
    "serving_kv_pool_bytes_per_shard":
        "KV page-pool bytes resident PER DEVICE (kv-head axis split over "
        "the mesh model axis)",
    # -- speculative decoding (docs/serving.md "Speculative decoding") ----
    "serving_spec_drafted_total":
        "draft tokens scored by the verify step (host drafter proposals "
        "the target model checked)",
    "serving_spec_accepted_total":
        "draft tokens accepted exactly (the sampled chain matched the "
        "draft) — each one is a decode step the engine did not pay",
    "serving_spec_accept_rate":
        "accepted / drafted over the engine lifetime (0 before any "
        "draft; PERF.md 'Reading the accept rate')",
    "serving_draft_steps_total":
        "drafter proposal passes that proposed at least one token "
        "(a ModelDrafter pass is ONE batched device dispatch for all "
        "decoding slots)",
    "serving_draft_ms":
        "wall ms per drafter proposal pass (host lookup or batched "
        "draft-model dispatch) — must stay well under the verify step "
        "it feeds for speculation to pay",
    "serving_spec_k_effective":
        "per-slot draft depth chosen each flush window (dynamic k: the "
        "accept-EWMA policy's output, 0..spec_k; static: spec_k) — mass "
        "near 0 means the workload does not sustain speculation",
    # -- chunked prefill / mixed-step token budget -------------------------
    "serving_step_tokens":
        "scheduled token rows per compiled step (decode rows + prefill "
        "chunk rows; bounded by max_step_tokens — the p99 inter-token "
        "latency bound)",
    "serving_prefill_chunks_total":
        "prompt chunks scheduled into mixed prefill/decode steps",
    "serving_mixed_steps_total":
        "compiled steps that carried at least one prefill chunk row",
    "serving_scan_steps_total":
        "decode bodies run inside scanned multi-step dispatches "
        "(decode_steps per flush; see serving_scan_flushes_total)",
    "serving_scan_flushes_total":
        "scanned multi-step dispatches (host boundaries) — steps/flushes "
        "reads back the effective decode_steps",
    "serving_decode_gap_ms":
        "pump-step gap decoding slots saw (ms between consecutive steps "
        "advancing decode rows — HOL-blocking prefill shows here)",
    "serving_tokens_generated_total": "tokens emitted across all requests",
    "serving_preemptions_total": "slots preempted by page-pool pressure",
    "serving_cancelled_total": "requests aborted by client cancel/disconnect",
    "serving_expired_total": "requests aborted by deadline expiry",
    # -- serving: front-end admission state -------------------------------
    "serving_inflight": "accepted-but-unfinished requests",
    "serving_max_inflight": "admission cap (num_slots + max_queue)",
    "serving_draining": "1 while the server refuses new work to drain",
    "serving_requests_accepted_total": "generate requests admitted",
    "serving_overload_total": "generate requests refused with overload",
    "serving_latency_seconds":
        "request/first-token/inter-token latency quantiles "
        "(labels: stat, quantile; bounded recent-sample windows)",
    "serving_latency_count": "samples recorded per latency stat (label: stat)",
    # -- fleet router (paddle_tpu/fleet/router.py) -------------------------
    "fleet_requests_accepted_total": "generate requests the router placed",
    "fleet_relay_latency_seconds":
        "router-tier relay latency quantiles (labels: stat, quantile; "
        "relay_token_latency = burst-honest inter-token gap — a scanned "
        "k-token burst charges each token gap/k)",
    "fleet_relay_latency_count":
        "samples recorded per router relay stat (label: stat)",
    "fleet_placements_total":
        "placements by policy decision (label: policy = "
        "affinity/least_loaded/random/disagg)",
    "fleet_retries_total":
        "requests transparently re-placed after replica death/circuit-open "
        "(only never-streamed requests retry)",
    "fleet_sheds_total":
        "requests refused with overload at the fleet level (every healthy "
        "replica saturated, none registered, or router draining)",
    "fleet_joins_total": "replica registrations (hello handshake passed)",
    "fleet_leaves_total":
        "replica departures (ctl leave, connection lost, heartbeat expiry)",
    "fleet_inflight": "requests routed and not yet finished",
    "fleet_replicas_registered": "replicas in the router's table",
    "fleet_replicas_healthy": "replicas placement may choose from",
    "fleet_replicas_draining":
        "replicas finishing in-flight work while refused new placements",
    "fleet_replicas_broken":
        "replicas with the circuit open (polled pump wedged/dead)",
    "fleet_affinity_keys":
        "prefix-affinity index entries (bounded LRU; first page-run -> "
        "replica)",
    "fleet_draining": "1 while the router refuses new work to drain",
    # -- disaggregated prefill/decode placement (docs/serving.md) ---------
    "fleet_kv_pushes_total":
        "disaggregated placements the router started (prefill_only sent "
        "to a prefill-tier replica with a push_to target)",
    "fleet_kv_push_failures_total":
        "disaggregated placements whose kv_push failed (done frame came "
        "back push_ok:false) — each falls back to colocated placement",
    "fleet_kv_fallbacks_total":
        "requests re-placed colocated after a disagg attempt failed "
        "(push failure, prefill replica death, decode tier gone)",
    "fleet_kv_pages_shipped_total":
        "KV pages the router observed shipped on successful kv_pushes "
        "(sum of pushed_pages off done frames)",
    # -- parameter server (paddle_tpu/pserver/) ----------------------------
    "pserver_version": "optimizer updates committed (the parameter version)",
    "pserver_pass_id": "training passes completed server-side",
    "pserver_trainers_active": "trainers the sync barrier waits for",
    "pserver_trainers_draining":
        "trainers finishing a final batch before leaving (never stall "
        "the barrier)",
    "pserver_updates_total": "optimizer applies (sync windows + async "
        "contributions) committed by the update thread",
    "pserver_grads_received_total": "send_grad frames accepted",
    "pserver_grads_discarded_total":
        "in-flight contributions discarded (dead trainer mid-window, or "
        "the drop-last convention at a pass barrier)",
    "pserver_async_rejected_total":
        "async gradients refused for exceeding max_staleness (the "
        "trainer must re-pull)",
    "pserver_async_staleness":
        "versions behind at async apply — the honest divergence signal "
        "of bounded-staleness training",
    "pserver_barrier_wait_seconds":
        "time a sync barrier waiter spent blocked until its window "
        "committed (straggler skew shows here)",
    "pserver_snapshots_total": "streaming checkpoints committed",
    "pserver_snapshot_seconds":
        "wall seconds per streaming checkpoint (capture is O(blocks) "
        "pointer copies; the write overlaps live send_grad traffic)",
    "pserver_blocks": "parameter/optimizer blocks held by this shard",
    "pserver_block_bytes": "bytes held by this shard's parameter blocks",
    "pserver_window_skew_ms":
        "per-window barrier-arrival skew (last arriver minus first, ms) "
        "on the shard-0 coordinator — the straggler signal",
    "pserver_apply_seconds":
        "update-thread wall per window commit (accumulate + optimizer "
        "apply, device-synced)",
    "pserver_update_lag_s":
        "seconds the update thread has been inside its current job — "
        "0 when idle; growing = a wedged optimizer apply",
    "pserver_update_alive":
        "1 while the update thread is running and error-free",
    # -- pump-thread heartbeat watchdog -----------------------------------
    "pump_alive":
        "1 while the engine pump is running (0 the moment it has fatally "
        "errored, even mid-unwind)",
    "pump_last_step_age_s":
        "seconds since the pump last completed a loop iteration — a wedged "
        "engine shows here before clients time out",
    # -- trainer -----------------------------------------------------------
    "trainer_pass_id": "passes completed",
    "trainer_cost": "mean cost of the last finished pass",
    "trainer_samples_per_sec": "throughput of the last finished pass",
    "trainer_batches_total": "batches trained since process start",
    "trainer_samples_total": "samples trained since process start",
    "trainer_host_phase_seconds":
        "host-phase duration quantiles from the global StatSet "
        "(labels: phase, quantile)",
    "trainer_host_phase_count": "timed occurrences per host phase",
    "trainer_host_phase_seconds_total": "accumulated seconds per host phase",
    "trainer_barrier_seconds":
        "BarrierTimer window quantiles: dispatch/sync/h2d/scan "
        "(labels: window, quantile)",
    # -- tracer ------------------------------------------------------------
    "trace_spans_recorded_total": "spans recorded since enable (incl. wrapped)",
    "trace_spans_dropped_total": "spans overwritten by ring wrap-around",
    "trace_ring_capacity":
        "span-ring capacity — dropped_total climbing against it means the "
        "trace window is shorter than the workload being debugged",
    # -- compile observability (obs/compile_watch.py) ----------------------
    "jit_compiles_total":
        "jit compiles observed per instrumented entry point (label: site)",
    "jit_compile_seconds":
        "accumulated compile+first-run wall seconds per site (label: site)",
    "jit_signatures":
        "distinct compiled signatures seen per site (label: site)",
    "jit_recompile_storms_total":
        "recompile-storm warnings fired per site (label: site)",
    # -- device-memory accounting (obs/hbm.py) -----------------------------
    "hbm_bytes_in_use":
        "device-reported bytes in use (absent when the backend, e.g. CPU, "
        "does not report)",
    "hbm_bytes_limit": "device-reported memory limit (absent on CPU)",
    "hbm_live_array_bytes": "total nbytes over jax.live_arrays()",
    "hbm_live_arrays": "count of live device arrays",
    "hbm_param_bytes": "bytes held by the model parameter pytree",
    "hbm_kv_pool_bytes": "bytes held by the paged KV cache pools",
    # -- flight recorder (obs/flight.py) -----------------------------------
    "flight_events_recorded_total":
        "flight-recorder events recorded (incl. wrapped)",
    "flight_events_dropped_total":
        "flight-recorder events overwritten by ring wrap-around",
    "postmortem_bundles_total": "postmortem bundles written by this process",
    # -- health plane (obs/timeseries.py + obs/slo.py) ---------------------
    "obs_history_series":
        "distinct metric series tracked by the in-memory history ring",
    "obs_history_samples_total":
        "sampling passes the history sampler has taken over the registry",
    "obs_history_sample_age_s":
        "seconds since the history sampler last walked the registry "
        "(-1 before the first pass) — a stuck sampler shows here",
    "obs_history_dropped_series_total":
        "series refused by the history ring's cardinality cap",
    "obs_slo_firing":
        "1 while the named SLO is firing (label: slo; burn-rate "
        "semantics in docs/observability.md 'Health plane')",
    "obs_slo_fired_total": "firing transitions per SLO (label: slo)",
}


def _validate_name(name: str) -> None:
    if not name or not all(c.isalnum() or c == "_" for c in name) \
            or name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple,
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._vals: dict[tuple, float] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} declared labels "
                f"{self.labelnames}, got {tuple(sorted(labels))}")
        return tuple(labels[k] for k in self.labelnames)

    def _labels_of(self, key: tuple) -> Optional[dict]:
        return dict(zip(self.labelnames, key)) if self.labelnames else None

    def samples(self) -> list[tuple]:
        with self._lock:
            items = list(self._vals.items())
        return [(self.name, self.kind, self._labels_of(k), v)
                for k, v in items]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        k = self._key(labels)
        with self._lock:
            self._vals[k] = self._vals.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._vals.get(self._key(labels), 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._vals[self._key(labels)] = float(value)

    def set_fn(self, fn: Callable[[], float], **labels) -> None:
        """Callback gauge: `fn` is sampled at render time (on the render
        thread — keep it a cheap read of GIL-atomic state)."""
        self._vals[self._key(labels)] = fn

    def value(self, **labels) -> float:
        v = self._vals.get(self._key(labels), 0.0)
        return float(v()) if callable(v) else v

    def samples(self) -> list[tuple]:
        with self._lock:
            items = list(self._vals.items())
        return [(self.name, self.kind, self._labels_of(k),
                 float(v()) if callable(v) else v)
                for k, v in items]


#: latency-shaped default buckets, seconds
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = b
        # per label-key: ([cumulative counts per bucket + inf], sum, count)

    def observe(self, value: float, **labels) -> None:
        k = self._key(labels)
        v = float(value)
        with self._lock:
            st = self._vals.get(k)
            if st is None:
                st = self._vals[k] = [[0] * (len(self.buckets) + 1),
                                      0.0, 0]
            for i, le in enumerate(self.buckets):
                if v <= le:
                    st[0][i] += 1
            st[0][-1] += 1                       # +Inf
            st[1] += v
            st[2] += 1

    def samples(self) -> list[tuple]:
        with self._lock:
            items = [(k, ([*st[0]], st[1], st[2]))
                     for k, st in self._vals.items()]
        out = []
        for k, (counts, total, n) in items:
            base = self._labels_of(k) or {}
            for i, le in enumerate(self.buckets):
                out.append((self.name + "_bucket", "histogram",
                            dict(base, le=f"{le:g}"), float(counts[i])))
            out.append((self.name + "_bucket", "histogram",
                        dict(base, le="+Inf"), float(counts[-1])))
            out.append((self.name + "_sum", "histogram",
                        self._labels_of(k), total))
            out.append((self.name + "_count", "histogram",
                        self._labels_of(k), float(n)))
        return out


class MetricsRegistry:
    """Named metric registry + render surface.  `strict=True` pins every
    metric name (declared or collector-emitted) to CATALOG."""

    def __init__(self, strict: bool = False):
        self.strict = strict
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], Iterable[tuple]]] = []

    # -- declaration -------------------------------------------------------
    def _declare(self, cls, name: str, help: str, labels, **kw):
        _validate_name(name)
        if self.strict and name not in CATALOG:
            raise ValueError(
                f"metric {name!r} is not in obs.metrics.CATALOG — add it "
                f"(and document it in docs/observability.md; "
                f"tools/check_metrics_names.py enforces the pairing)")
        help = help or CATALOG.get(name, "")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} re-declared as {cls.kind} with "
                        f"labels {tuple(labels)} (was {m.kind} "
                        f"{m.labelnames})")
                return m
            m = cls(name, help, tuple(labels), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._declare(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._declare(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, labels, buckets=buckets)

    def register_collector(self, fn: Callable[[], Iterable[tuple]]) -> None:
        """`fn()` -> iterable of (name, kind, labels|None, value), pulled
        at every render/snapshot — the adapter hook for stat objects that
        keep their own storage (StatSet, BarrierTimer, engine counters)."""
        with self._lock:
            self._collectors.append(fn)

    # -- reading -----------------------------------------------------------
    def _all_samples(self) -> list[tuple]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out = []
        for m in metrics:
            out.extend(m.samples())
        for fn in collectors:
            for name, kind, labels, value in fn():
                if self.strict and \
                        self._family_of(name, kind) not in CATALOG:
                    raise ValueError(
                        f"collector emitted uncataloged metric {name!r}")
                out.append((name, kind, labels, value))
        return out

    @staticmethod
    def _family_of(name: str, kind: str) -> str:
        """Metric family a sample belongs to: histogram samples group
        under their base name (x_bucket/x_sum/x_count -> x), which is
        where the exposition format wants the one HELP/TYPE pair."""
        if kind == "histogram":
            for suf in ("_bucket", "_sum", "_count"):
                if name.endswith(suf):
                    return name[: -len(suf)]
        return name

    def samples(self) -> list[tuple]:
        """Public kinded view: [(name, kind, labels|None, value)] — the
        raw feed `render()`/`snapshot()` are built from.  The history
        sampler (obs/timeseries.py) reads this rather than `snapshot()`
        because downsampling needs `kind` (counters store as deltas),
        which the flat dict loses."""
        return self._all_samples()

    def render(self) -> str:
        """Prometheus text exposition (text/plain; version 0.0.4)."""
        families: dict[str, dict] = {}
        for name, kind, labels, value in self._all_samples():
            base = self._family_of(name, kind)
            fam = families.setdefault(base, {"kind": kind, "samples": []})
            fam["samples"].append((name, labels, value))
        lines = []
        for base in sorted(families):
            fam = families[base]
            help = self._metrics[base].help if base in self._metrics \
                else CATALOG.get(base, "")
            if help:
                lines.append(f"# HELP {base} {help}")
            lines.append(f"# TYPE {base} {fam['kind']}")
            for name, labels, value in fam["samples"]:
                v = f"{value:.10g}" if isinstance(value, float) else value
                lines.append(f"{name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """Flat {name or name{k=v,...}: value} dict — the metrics.jsonl
        record shape."""
        return {name + _fmt_labels(labels): value
                for name, _kind, labels, value in self._all_samples()}


# -- collector adapters for the pre-existing stat systems -------------------

def statset_collector(statset, metric: str, count_metric: str,
                      label: str = "stat", qs=(50.0, 90.0, 99.0),
                      total_metric: Optional[str] = None):
    """Expose a utils/stat.py StatSet as quantile gauges + sample counts.
    Pure read-time adapter: the StatSet keeps its owner and its per-Stat
    lock; quantiles come from its bounded recent-sample windows."""

    def collect():
        out = []
        for name in sorted(statset.stats):
            s = statset.stats.get(name)
            if s is None:
                continue
            for q, v in statset.percentiles(name, qs).items():
                out.append((metric, "gauge",
                            {label: name, "quantile": q}, v))
            out.append((count_metric, "counter", {label: name},
                        float(s.count)))
            if total_metric is not None:
                out.append((total_metric, "counter", {label: name},
                            float(s.total_s)))
        return out

    return collect


def barrier_collector(bt, metric: str = "trainer_barrier_seconds"):
    """Expose a BarrierTimer's rolling windows (dispatch/sync/h2d/scan)
    as quantile gauges, in seconds."""

    def collect():
        out = []
        for window, pct in bt.local_summary().items():     # values in ms
            for q, v in pct.items():
                out.append((metric, "gauge",
                            {"window": window, "quantile": q}, v / 1e3))
        return out

    return collect


def tracer_collector(tracer):
    """Expose the span tracer's ring accounting: recorded/dropped totals
    plus the ring capacity they are read against — the Tracer overwrites
    silently when full, so the dropped counter (regression-tested in
    tests/test_obs.py) is the ONLY place that loss is visible."""

    def collect():
        return [
            ("trace_spans_recorded_total", "counter", None,
             float(tracer.recorded)),
            ("trace_spans_dropped_total", "counter", None,
             float(tracer.dropped)),
            ("trace_ring_capacity", "gauge", None,
             float(tracer.capacity)),
        ]

    return collect
