"""Device-memory accounting: HBM gauges with a CPU-safe fallback.

The KV page pools and the parameter arrays are the two deliberate HBM
tenants of a serving replica; everything else (prefill activations, a
leaked buffer from a bug) shows up as the gap between them and the
device's own accounting.  The production failure mode this makes visible
is HBM exhaustion of the page pools (the headroom signal the TPU serving
literature treats as first-class, arXiv:2605.25645): when
`hbm_bytes_in_use` approaches `hbm_bytes_limit` while `hbm_kv_pool_bytes`
is flat, the leak is NOT the pool — and vice versa.

Three sources, each degrading independently (CPU test runs must keep the
metrics frame renderable with zero of them available):

  * `device_memory_stats()` — the backend's own accounting
    (`Device.memory_stats()`: TPU/GPU report bytes_in_use/limit; the CPU
    backend returns None or raises, and the gauges are simply absent);
  * `live_array_bytes()` — `jax.live_arrays()` walked for nbytes: every
    on-device buffer the process still references, whatever allocated it;
  * `tree_bytes()` / `kv_pool_bytes()` — duck-typed nbytes sums over the
    params pytree and the paged-KV pools (always available, no jax
    import needed at module load).

`hbm_collector()` adapts them into the obs.metrics registries (the
server's `metrics` frame and the trainer's `metrics.jsonl`) at render
time — scrape cadence, never the token hot path.
"""

from __future__ import annotations

from typing import Callable, Optional


def device_memory_stats() -> Optional[dict]:
    """The first addressable device's memory_stats(), or None when the
    backend does not report (CPU) or jax is absent entirely."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        return dict(stats) if stats else None
    except Exception:                      # noqa: BLE001 — no backend = no gauge
        return None


def live_array_bytes() -> Optional[tuple[int, int]]:
    """(total_nbytes, count) over jax.live_arrays(), or None when the
    probe is unavailable (old jax / no jax)."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:                      # noqa: BLE001
        return None
    total = count = 0
    for a in arrays:
        try:
            total += int(a.nbytes)
            count += 1
        except Exception:                  # noqa: BLE001 — deleted buffer race
            continue
    return total, count


def tree_bytes(tree) -> int:
    """nbytes summed over array-ish leaves of a nested dict/list/tuple —
    duck-typed so it works on np arrays, jax arrays, and mixed pytrees."""
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        elif hasattr(x, "nbytes"):
            try:
                total += int(x.nbytes)
            except Exception:              # noqa: BLE001
                continue
    return total


def kv_pool_bytes(kv) -> int:
    """Bytes held by a PagedKVCache's per-layer page pools."""
    return tree_bytes(kv.pools)


def hbm_collector(params_fn: Optional[Callable] = None,
                  kv_fn: Optional[Callable] = None):
    """obs.metrics collector for the hbm_* gauges.

    `params_fn()` -> the live params pytree (a callable, not a snapshot —
    donated buffers rebind every step); `kv_fn()` -> the PagedKVCache.
    Either may be None (the trainer has no KV pool; a bare tool has no
    params).  Backend gauges are EMITTED ONLY WHEN THE PROBE ANSWERS —
    an absent `hbm_bytes_in_use` means "backend does not report", a zero
    would lie."""

    def collect():
        out = []
        stats = device_memory_stats()
        if stats is not None:
            if "bytes_in_use" in stats:
                out.append(("hbm_bytes_in_use", "gauge", None,
                            float(stats["bytes_in_use"])))
            if "bytes_limit" in stats:
                out.append(("hbm_bytes_limit", "gauge", None,
                            float(stats["bytes_limit"])))
        live = live_array_bytes()
        if live is not None:
            out.append(("hbm_live_array_bytes", "gauge", None,
                        float(live[0])))
            out.append(("hbm_live_arrays", "gauge", None, float(live[1])))
        if params_fn is not None:
            out.append(("hbm_param_bytes", "gauge", None,
                        float(tree_bytes(params_fn()))))
        if kv_fn is not None:
            out.append(("hbm_kv_pool_bytes", "gauge", None,
                        float(kv_pool_bytes(kv_fn()))))
        return out

    return collect


def hbm_snapshot(params=None, kv=None) -> dict:
    """One-shot dict of everything measurable — the postmortem-bundle
    shape (and a convenient REPL probe)."""
    out: dict = {}
    stats = device_memory_stats()
    if stats is not None:
        out["device_memory_stats"] = stats
    live = live_array_bytes()
    if live is not None:
        out["live_array_bytes"], out["live_arrays"] = live
    if params is not None:
        out["param_bytes"] = tree_bytes(params)
    if kv is not None:
        out["kv_pool_bytes"] = kv_pool_bytes(kv)
    return out
