"""Layer constructors — the user-facing model DSL.

The TPU framework's equivalent of the reference's layer DSL
(ref: python/paddle/trainer_config_helpers/layers.py, 4,610 LoC: fc_layer:832,
lstmemory:993, grumemory:1100, recurrent_group:2786, beam_search:3087,
memory:2444, mixed_layer:703, img_conv_layer, cost layers, ...).  Each
constructor appends LayerConfig/ParameterConfig records to the active
ConfigContext and returns a LayerOutput handle; size inference follows the
reference's rules so stock configs produce the same graph shapes.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Union

from paddle_tpu.config.schema import (
    ConvConfig,
    EvaluatorConfig,
    GeneratorConfig,
    LayerConfig,
    LayerInput,
    MemoryConfig,
    NormConfig,
    OperatorConfig,
    ParameterConfig,
    PoolConfig,
    ProjectionConfig,
    SubModelConfig,
)
from paddle_tpu.dsl.activations import BaseActivation, LinearActivation, SigmoidActivation, TanhActivation, act_name
from paddle_tpu.dsl.attrs import ExtraLayerAttribute, ParameterAttribute
from paddle_tpu.dsl.base import LayerOutput, current_context
from paddle_tpu.dsl.poolings import AvgPooling, BasePoolingType, FirstPooling, LastPooling, MaxPooling

__all__ = [
    "data_layer", "fc_layer", "embedding_layer", "mixed_layer", "addto_layer",
    "concat_layer", "dropout_layer", "full_matrix_projection",
    "trans_full_matrix_projection", "identity_projection", "table_projection",
    "dotmul_projection", "scaling_projection", "context_projection",
    "conv_projection", "dotmul_operator", "conv_operator", "default_device",
    "pooling_layer", "last_seq", "first_seq", "expand_layer", "seq_concat_layer",
    "seq_reshape_layer", "repeat_layer",
    "lstmemory", "grumemory", "recurrent_layer", "lstm_step_layer", "gru_step_layer",
    "mdlstm_layer", "sub_seq_layer",
    "img_conv_layer", "img_pool_layer", "img_cmrnorm_layer", "batch_norm_layer",
    "bilinear_interp_layer", "block_expand_layer", "maxout_layer", "spp_layer",
    "conv_shift_layer", "multi_head_attention_layer", "moe_layer",
    "layer_norm_layer",
    "maxid_layer", "sampling_id_layer", "eos_layer",
    "cos_sim", "cos_sim_vecmat", "trans_layer", "resize_layer",
    "slope_intercept_layer", "scaling_layer", "interpolation_layer",
    "power_layer", "linear_comb_layer", "convex_comb_layer", "outer_prod_layer",
    "tensor_layer", "multiplex_layer", "selective_fc_layer", "print_layer",
    "classification_cost", "regression_cost", "cross_entropy",
    "cross_entropy_with_selfnorm", "soft_binary_class_cross_entropy",
    "multi_binary_label_cross_entropy", "rank_cost", "lambda_cost",
    "huber_cost", "sum_cost", "auc_validation", "pnpair_validation",
    "crf_layer", "crf_decoding_layer", "ctc_layer", "nce_layer", "hsigmoid",
    "recurrent_group", "memory", "StaticInput", "SubsequenceInput",
    "GeneratedInput", "BaseGeneratedInput", "beam_search", "sub_network",
    "get_output_layer",
    "LayerOutput",
    "AggregateLevel", "ExpandLevel", "LayerType", "out_prod_layer",
    "sum_to_one_norm_layer",
]


# ---------------------------------------------------------------------------
# parameter helpers
# ---------------------------------------------------------------------------

def _make_param(
    layer_name: str,
    idx: Union[int, str],
    dims: list[int],
    attr: Optional[ParameterAttribute],
    *,
    is_bias: bool = False,
    sparse_size: int = 0,
) -> str:
    """Create (or reuse) a ParameterConfig; returns its name.  Naming follows
    the reference: _<layer>.w<i> / _<layer>.wbias (ref: config_parser.py
    Layer.create_input_parameter / create_bias_parameter)."""
    ctx = current_context()
    if attr is not None and attr.name:
        if ctx.has_parameter(attr.name):
            return attr.name  # shared parameter
        name = attr.name
    else:
        name = f"_{layer_name}.wbias" if is_bias else f"_{layer_name}.w{idx}"
    size = 1
    for d in dims:
        size *= d
    cfg = ParameterConfig(name=name, size=size, dims=list(dims))
    if is_bias:
        cfg.initial_strategy = "zero"
        cfg.initial_smart = False
    else:
        cfg.initial_smart = True  # std = 1/sqrt(fan_in) default (ref rule)
    if attr is not None:
        attr.apply(cfg)
    ctx.add_parameter(cfg)
    return name


def _bias_name(layer_name: str, bias_attr, dims: list[int]) -> str:
    """bias_attr semantics follow the reference: False = no bias, True/None =
    default bias, ParameterAttribute = custom."""
    if bias_attr is False:
        return ""
    attr = bias_attr if isinstance(bias_attr, ParameterAttribute) else None
    return _make_param(layer_name, "bias", dims, attr, is_bias=True)


def _layer_attr_fields(cfg: LayerConfig, layer_attr: Optional[ExtraLayerAttribute]) -> None:
    if layer_attr is not None:
        if layer_attr.drop_rate is not None:
            cfg.drop_rate = layer_attr.drop_rate
        if layer_attr.device is not None:
            cfg.device = layer_attr.device


def _name(name: Optional[str], prefix: str) -> str:
    return name if name else current_context().unique_name(prefix)


# ---------------------------------------------------------------------------
# data & fc
# ---------------------------------------------------------------------------

def data_layer(name: str, size: int, height: int = 0, width: int = 0) -> LayerOutput:
    """(ref: layers.py data_layer; DataLayer.cpp).  With height/width set,
    the output carries image geometry for downstream conv size inference."""
    ctx = current_context()
    cfg = LayerConfig(name=name, type="data", size=size)
    out = LayerOutput(name, "data", size)
    if height and width:
        cfg.attrs["height"] = height
        cfg.attrs["width"] = width
        out.img_size = width
        out.img_size_y = height
        out.num_filters = size // (height * width)
    ctx.add_layer(cfg)
    ctx.model.input_layer_names.append(name)
    return out


def fc_layer(
    input: Union[LayerOutput, Sequence[LayerOutput]],
    size: int,
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    param_attr: Optional[Union[ParameterAttribute, list]] = None,
    bias_attr=None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """(ref: layers.py fc_layer:832; FullyConnectedLayer.cpp)."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    name = _name(name, "fc_layer")
    if act is None:
        act = TanhActivation()
    attrs = param_attr if isinstance(param_attr, list) else [param_attr] * len(inputs)
    cfg = LayerConfig(name=name, type="fc", size=size, active_type=act_name(act))
    for i, (inp, pa) in enumerate(zip(inputs, attrs)):
        pname = _make_param(name, i, [inp.size, size], pa)
        cfg.inputs.append(LayerInput(input_layer_name=inp.name, input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "fc", size, parents=inputs, activation=act,
                       seq_level=inputs[0].seq_level)


def embedding_layer(
    input: LayerOutput, size: int,
    name: Optional[str] = None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """Table lookup over integer ids (ref: layers.py embedding_layer —
    implemented as mixed + table_projection, same as the reference)."""
    with mixed_layer(size=size, name=name, act=LinearActivation(),
                     bias_attr=False, layer_attr=layer_attr) as m:
        m += table_projection(input=input, size=size, param_attr=param_attr)
    return m


# ---------------------------------------------------------------------------
# mixed layer + projections/operators
# ---------------------------------------------------------------------------

class _Projection:
    """A pending projection: (source LayerOutput, ProjectionConfig, param spec)."""

    def __init__(self, source: LayerOutput, proj: ProjectionConfig,
                 param_dims: Optional[list[int]], param_attr, size: int):
        self.source = source
        self.proj = proj
        self.param_dims = param_dims
        self.param_attr = param_attr
        self.size = size


class _Operator:
    def __init__(self, sources: list[LayerOutput], op: OperatorConfig, size: int):
        self.sources = sources
        self.op = op
        self.size = size


class MixedLayer(LayerOutput):
    """Context-manager / += DSL for mixed layers (ref: layers.py mixed_layer:703)."""

    def __init__(self, size: int, name: str, act, bias_attr, layer_attr):
        super().__init__(name, "mixed", size)
        self._act = act
        self._bias_attr = bias_attr
        self._layer_attr = layer_attr
        self._projs: list[_Projection] = []
        self._ops: list[_Operator] = []
        self._finalized = False

    def __iadd__(self, other):
        assert not self._finalized, "mixed_layer already finalized"
        if isinstance(other, _Projection):
            self._projs.append(other)
        elif isinstance(other, _Operator):
            self._ops.append(other)
        else:
            raise TypeError(f"cannot add {type(other)} to mixed_layer")
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is None:
            self._finalize()
        return False

    def _finalize(self):
        if self._finalized:
            return
        self._finalized = True
        if not self.size:
            # infer from first projection/operator
            self.size = self._projs[0].size if self._projs else self._ops[0].size
        cfg = LayerConfig(name=self.name, type="mixed", size=self.size,
                          active_type=act_name(self._act))
        seq_level = 0
        for i, p in enumerate(self._projs):
            if not p.proj.output_size:
                p.proj.output_size = self.size
            if p.param_dims is None:
                # projection declared without an explicit size (the
                # reference allows e.g. full_matrix_projection(input=x)
                # inside mixed_layer(size=N)): dims resolve against the
                # mixed layer's size at finalize time
                if p.proj.type in ("fc", "full_matrix", "table"):
                    p.param_dims = [p.proj.input_size, self.size]
                elif p.proj.type == "trans_full_matrix":
                    p.param_dims = [self.size, p.proj.input_size]
            pname = ""
            if p.param_dims is not None:
                pname = _make_param(self.name, i, p.param_dims, p.param_attr)
            cfg.inputs.append(LayerInput(
                input_layer_name=p.source.name, input_parameter_name=pname, proj=p.proj))
            self.parents.append(p.source)
            seq_level = max(seq_level, p.source.seq_level)
        n_proj = len(self._projs)
        for op in self._ops:
            op.op.input_indices = list(range(len(cfg.inputs), len(cfg.inputs) + len(op.sources)))
            op.op.input_sizes = [s.size for s in op.sources]
            if not op.op.output_size:
                op.op.output_size = self.size
            for s in op.sources:
                cfg.inputs.append(LayerInput(input_layer_name=s.name))
                self.parents.append(s)
            cfg.operators.append(op.op)
        cfg.bias_parameter_name = _bias_name(self.name, self._bias_attr, [1, self.size])
        _layer_attr_fields(cfg, self._layer_attr)
        self.seq_level = seq_level
        current_context().add_layer(cfg)


def mixed_layer(
    size: int = 0,
    input: Optional[Sequence] = None,
    name: Optional[str] = None,
    act: Optional[BaseActivation] = None,
    bias_attr=False,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> MixedLayer:
    """(ref: layers.py mixed_layer:703)."""
    name = _name(name, "mixed")
    if act is None:
        act = LinearActivation()
    m = MixedLayer(size=size, name=name, act=act, bias_attr=bias_attr,
                   layer_attr=layer_attr)
    if input is not None:
        for p in input if isinstance(input, (list, tuple)) else [input]:
            m += p
        m._finalize()
    return m


def full_matrix_projection(input: LayerOutput, size: int = 0,
                           param_attr: Optional[ParameterAttribute] = None) -> _Projection:
    """(ref: layers.py full_matrix_projection:308; FullMatrixProjection.cpp)."""
    proj = ProjectionConfig(type="fc", input_size=input.size, output_size=size)
    return _Projection(input, proj, [input.size, size] if size else None, param_attr, size)


def trans_full_matrix_projection(input: LayerOutput, size: int = 0,
                                 param_attr: Optional[ParameterAttribute] = None) -> _Projection:
    """(ref: TransposedFullMatrixProjection.cpp)."""
    proj = ProjectionConfig(type="trans_full_matrix", input_size=input.size, output_size=size)
    return _Projection(input, proj, [size, input.size] if size else None, param_attr, size)


def identity_projection(input: LayerOutput, offset: int = 0) -> _Projection:
    """(ref: IdentityProjection.cpp). Offset slicing unsupported-yet."""
    assert offset == 0, "identity_projection offset not yet supported"
    proj = ProjectionConfig(type="identity", input_size=input.size, output_size=input.size)
    return _Projection(input, proj, None, None, input.size)


def table_projection(input: LayerOutput, size: int = 0,
                     param_attr: Optional[ParameterAttribute] = None) -> _Projection:
    """(ref: TableProjection.cpp) — embedding rows; input must be ids."""
    proj = ProjectionConfig(type="table", input_size=input.size, output_size=size)
    return _Projection(input, proj, [input.size, size] if size else None, param_attr, size)


def dotmul_projection(input: LayerOutput,
                      param_attr: Optional[ParameterAttribute] = None) -> _Projection:
    """(ref: DotMulProjection.cpp): out = x .* w."""
    proj = ProjectionConfig(type="dot_mul", input_size=input.size, output_size=input.size)
    return _Projection(input, proj, [1, input.size], param_attr, input.size)


def scaling_projection(input: LayerOutput,
                       param_attr: Optional[ParameterAttribute] = None) -> _Projection:
    """(ref: ScalingProjection.cpp): out = w[0] * x, one learned scalar."""
    proj = ProjectionConfig(type="scaling", input_size=input.size, output_size=input.size)
    return _Projection(input, proj, [1, 1], param_attr, input.size)


def default_device(device: int = 0) -> None:
    """No-op: the reference pins layers to GPUs (ref: config_parser.py
    default_device); here placement is mesh sharding, set via
    ParameterAttribute.partition_spec / Trainer(mesh=...)."""
    return None


def context_projection(
    input: LayerOutput, context_len: int, context_start: Optional[int] = None,
    padding_attr=False,
) -> _Projection:
    """Sliding window concat over time (ref: layers.py context_projection:574;
    ContextProjection.cpp)."""
    start = context_start if context_start is not None else -(context_len // 2)
    trainable = isinstance(padding_attr, ParameterAttribute)
    proj = ProjectionConfig(
        type="context", input_size=input.size,
        output_size=input.size * context_len,
        context_start=start, context_length=context_len,
        trainable_padding=trainable)
    total_pad = max(0, -start) + max(0, start + context_len - 1)
    dims = [total_pad, input.size] if trainable else None
    return _Projection(input, proj, dims, padding_attr if trainable else None,
                       input.size * context_len)


def conv_projection(
    input: LayerOutput, filter_size: int, num_filters: int,
    num_channels: Optional[int] = None, stride: int = 1, padding: int = 0,
    groups: int = 1, param_attr: Optional[ParameterAttribute] = None,
) -> _Projection:
    """(ref: ConvProjection.cpp)."""
    from paddle_tpu.graph.layers_conv import conv_output_size
    channels = num_channels if num_channels else input.num_filters
    img = input.img_size if input.img_size else int(math.sqrt(input.size // channels))
    out_x = conv_output_size(img, filter_size, stride, padding)
    conv = ConvConfig(filter_size=filter_size, channels=channels, stride=stride,
                      padding=padding, groups=groups, img_size=img, img_size_y=img,
                      output_x=out_x, output_y=out_x)
    out_size = num_filters * out_x * out_x
    proj = ProjectionConfig(type="conv", input_size=input.size, output_size=out_size,
                            conv=conv, num_filters=num_filters)
    dims = [num_filters, channels // groups * filter_size * filter_size]
    p = _Projection(input, proj, dims, param_attr, out_size)
    return p


def dotmul_operator(a: LayerOutput, b: LayerOutput, scale: float = 1.0) -> _Operator:
    """(ref: DotMulOperator.cpp): out += scale * a .* b."""
    op = OperatorConfig(type="dot_mul", dotmul_scale=scale, output_size=a.size)
    return _Operator([a, b], op, a.size)


def conv_operator(
    img: LayerOutput, filter: LayerOutput, filter_size: int, num_filters: int,
    num_channels: Optional[int] = None, stride: int = 1, padding: int = 0,
) -> _Operator:
    """Per-sample-filter convolution (ref: layers.py conv_operator:3317)."""
    from paddle_tpu.graph.layers_conv import conv_output_size
    channels = num_channels if num_channels else img.num_filters
    imgsz = img.img_size if img.img_size else int(math.sqrt(img.size // channels))
    out_x = conv_output_size(imgsz, filter_size, stride, padding)
    conv = ConvConfig(filter_size=filter_size, channels=channels, stride=stride,
                      padding=padding, img_size=imgsz, img_size_y=imgsz,
                      output_x=out_x, output_y=out_x)
    out_size = num_filters * out_x * out_x
    op = OperatorConfig(type="conv", conv=conv, num_filters=num_filters,
                        output_size=out_size)
    return _Operator([img, filter], op, out_size)


# ---------------------------------------------------------------------------
# simple combination layers
# ---------------------------------------------------------------------------

def _simple_layer(type_: str, inputs: list[LayerOutput], size: int, *,
                  name: Optional[str] = None, act=None, bias_attr=False,
                  layer_attr=None, cfg_extra: Optional[dict] = None,
                  params: Optional[list] = None,
                  prefix: Optional[str] = None) -> LayerOutput:
    name = _name(name, prefix or type_)
    cfg = LayerConfig(name=name, type=type_, size=size, active_type=act_name(act))
    for i, inp in enumerate(inputs):
        li = LayerInput(input_layer_name=inp.name)
        if params and params[i] is not None:
            li.input_parameter_name = _make_param(name, i, params[i][0], params[i][1])
        cfg.inputs.append(li)
    if bias_attr is not False:
        cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    if cfg_extra:
        for k, v in cfg_extra.items():
            if hasattr(cfg, k) and k != "attrs":
                setattr(cfg, k, v)
            else:
                cfg.attrs[k] = v
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    seq_level = max((i.seq_level for i in inputs), default=0)
    return LayerOutput(name, type_, size, parents=inputs, seq_level=seq_level)


def addto_layer(input: Sequence[LayerOutput], act=None, name=None,
                bias_attr=False, layer_attr=None) -> LayerOutput:
    """(ref: AddtoLayer.cpp)."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    out = _simple_layer("addto", inputs, inputs[0].size, name=name, act=act,
                        bias_attr=bias_attr, layer_attr=layer_attr)
    # elementwise add preserves image geometry (residual shortcuts feed
    # pooling/conv downstream — ref: AddtoLayer keeps the input's frame size)
    out.num_filters = inputs[0].num_filters
    out.img_size = inputs[0].img_size
    out.img_size_y = inputs[0].img_size_y
    return out


def concat_layer(input: Sequence[LayerOutput], act=None, name=None,
                 layer_attr=None) -> LayerOutput:
    """(ref: ConcatenateLayer.cpp)."""
    inputs = list(input)
    size = sum(i.size for i in inputs)
    return _simple_layer("concat", inputs, size, name=name, act=act,
                         layer_attr=layer_attr)


def dropout_layer(input: LayerOutput, dropout_rate: float, name=None) -> LayerOutput:
    """(ref: networks.py dropout_layer:1359 — addto with dropout attr)."""
    return addto_layer(input=[input], name=name,
                       layer_attr=ExtraLayerAttribute(drop_rate=dropout_rate))


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------

def pooling_layer(input: LayerOutput, pooling_type: Optional[BasePoolingType] = None,
                  name=None, bias_attr=False, agg_level: str = "to_no_sequence",
                  layer_attr=None) -> LayerOutput:
    """Sequence pooling (ref: layers.py pooling_layer; SequencePoolLayer.cpp).

    agg_level only matters for NESTED (sub-sequence) inputs:
    AggregateLevel.EACH_TIMESTEP ('non-seq', the reference's default —
    this function's own default behaves the same) pools over ALL
    timesteps ignoring sub boundaries; AggregateLevel.EACH_SEQUENCE
    ('seq') pools each sub-sequence to one vector, giving a sequence
    output."""
    pt = pooling_type or MaxPooling()
    extra: dict[str, Any] = {}
    type_ = pt.name
    if isinstance(pt, (AvgPooling,)) or getattr(pt, "strategy", None):
        extra["average_strategy"] = getattr(pt, "strategy", "average")
    if getattr(pt, "select_first", False):
        extra["select_first"] = True
    if agg_level in ("non-seq", "seq"):      # the AggregateLevel constants
        extra["trans_type"] = agg_level      # the schema field for levels
    out = _simple_layer(type_, [input], input.size, name=name, bias_attr=bias_attr,
                        layer_attr=layer_attr, cfg_extra=extra, prefix="pool")
    out.seq_level = 0 if agg_level == "non-seq" \
        else max(input.seq_level - 1, 0)
    return out


def last_seq(input: LayerOutput, name=None, agg_level: str = "to_no_sequence",
             layer_attr=None) -> LayerOutput:
    """(ref: layers.py last_seq; SequenceLastInstanceLayer.cpp).
    agg_level as in pooling_layer (nested inputs only)."""
    extra = ({"trans_type": agg_level}
             if agg_level in ("non-seq", "seq") else None)
    out = _simple_layer("seqlastins", [input], input.size, name=name,
                        layer_attr=layer_attr, cfg_extra=extra,
                        prefix="seqlastins")
    out.seq_level = 0 if agg_level == "non-seq" \
        else max(input.seq_level - 1, 0)
    return out


def first_seq(input: LayerOutput, name=None, agg_level: str = "to_no_sequence",
              layer_attr=None) -> LayerOutput:
    """(ref: layers.py first_seq).  agg_level as in pooling_layer."""
    extra: dict[str, Any] = {"select_first": True}
    if agg_level in ("non-seq", "seq"):
        extra["trans_type"] = agg_level
    out = _simple_layer("seqlastins", [input], input.size, name=name,
                        layer_attr=layer_attr, cfg_extra=extra,
                        prefix="seqfirstins")
    out.seq_level = 0 if agg_level == "non-seq" \
        else max(input.seq_level - 1, 0)
    return out


def expand_layer(input: LayerOutput, expand_as: LayerOutput, name=None,
                 bias_attr=False, expand_level: str = "from_no_sequence",
                 layer_attr=None) -> LayerOutput:
    """(ref: ExpandLayer.cpp)."""
    out = _simple_layer("expand", [input, expand_as], input.size, name=name,
                        bias_attr=bias_attr, layer_attr=layer_attr, prefix="expand")
    out.seq_level = expand_as.seq_level
    return out


def repeat_layer(input: LayerOutput, num_repeats: int, name=None) -> LayerOutput:
    """Tile features (ref: FeatureMapExpandLayer.cpp)."""
    return _simple_layer("featmap_expand", [input], input.size * num_repeats,
                         name=name, cfg_extra={"num_filters": num_repeats},
                         prefix="repeat")


def seq_concat_layer(a: LayerOutput, b: LayerOutput, name=None,
                     layer_attr=None) -> LayerOutput:
    """(ref: SequenceConcatLayer.cpp)."""
    assert a.size == b.size
    return _simple_layer("seqconcat", [a, b], a.size, name=name,
                         layer_attr=layer_attr, prefix="seqconcat")


def seq_reshape_layer(input: LayerOutput, reshape_size: int, name=None,
                      act=None, layer_attr=None, bias_attr=False) -> LayerOutput:
    """(ref: SequenceReshapeLayer.cpp)."""
    return _simple_layer("seqreshape", [input], reshape_size, name=name, act=act,
                         bias_attr=bias_attr, layer_attr=layer_attr,
                         prefix="seqreshape")


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------

def lstmemory(
    input: LayerOutput,
    name: Optional[str] = None,
    reverse: bool = False,
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    state_act: Optional[BaseActivation] = None,
    bias_attr=None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """LSTM over a pre-projected 4x input (ref: layers.py lstmemory:993;
    LstmLayer.cpp).  input.size must be 4*hidden; bias is [7*hidden] with
    peepholes, matching the reference."""
    assert input.size % 4 == 0, "lstmemory input must be 4 * hidden_size"
    size = input.size // 4
    name = _name(name, "lstmemory")
    cfg = LayerConfig(name=name, type="lstmemory", size=size,
                      active_type=act_name(act or TanhActivation()),
                      reversed=reverse)
    cfg.attrs["active_gate_type"] = act_name(gate_act or SigmoidActivation())
    cfg.attrs["active_state_type"] = act_name(state_act or TanhActivation())
    pname = _make_param(name, 0, [size, size * 4], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size * 7])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "lstmemory", size, parents=[input],
                       seq_level=input.seq_level)


def grumemory(
    input: LayerOutput,
    name: Optional[str] = None,
    reverse: bool = False,
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    bias_attr=None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """GRU over a pre-projected 3x input (ref: layers.py grumemory:1100;
    GatedRecurrentLayer.cpp)."""
    assert input.size % 3 == 0, "grumemory input must be 3 * hidden_size"
    size = input.size // 3
    name = _name(name, "gru")
    cfg = LayerConfig(name=name, type="gated_recurrent", size=size,
                      active_type=act_name(act or TanhActivation()),
                      reversed=reverse)
    cfg.attrs["active_gate_type"] = act_name(gate_act or SigmoidActivation())
    pname = _make_param(name, 0, [size, size * 3], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size * 3])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "gated_recurrent", size, parents=[input],
                       seq_level=input.seq_level)


def mdlstm_layer(
    input: LayerOutput,
    height: int,
    width: int,
    name: Optional[str] = None,
    directions=(True, True),
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    state_act: Optional[BaseActivation] = None,
    bias_attr=None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """2-D MDLSTM over a pre-projected 5x input grid (ref: MDLstmLayer.cpp:
    weight [D, 5D], bias [(5+4)D] incl. peepholes)."""
    assert input.size % 5 == 0, "mdlstm_layer input must be 5 * hidden_size"
    size = input.size // 5
    name = _name(name, "mdlstm")
    cfg = LayerConfig(name=name, type="mdlstmemory", size=size,
                      active_type=act_name(act or TanhActivation()))
    cfg.attrs["active_gate_type"] = act_name(gate_act or SigmoidActivation())
    cfg.attrs["active_state_type"] = act_name(state_act or TanhActivation())
    cfg.attrs["height"] = height
    cfg.attrs["width"] = width
    cfg.attrs["directions"] = tuple(bool(d) for d in directions)
    pname = _make_param(name, 0, [size, size * 5], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    if bias_attr is False:
        raise ValueError("mdlstm_layer requires a bias parameter — it carries "
                         "the peephole weights (ref: MDLstmLayer.cpp init "
                         "LOG(FATAL) without bias)")
    cfg.bias_parameter_name = _bias_name(name, bias_attr if bias_attr is not None else True,
                                         [1, size * 9])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "mdlstmemory", size, parents=[input],
                       seq_level=input.seq_level)


def sub_seq_layer(input: LayerOutput, offsets: LayerOutput, sizes: LayerOutput,
                  name=None, bias_attr=False, layer_attr=None) -> LayerOutput:
    """Per-sequence slice by offset/size inputs (ref: SubSequenceLayer.cpp)."""
    return _simple_layer("subseq", [input, offsets, sizes], input.size,
                         name=name, bias_attr=bias_attr, layer_attr=layer_attr,
                         prefix="subseq")


def lstm_step_layer(input: LayerOutput, state: LayerOutput, size: int,
                    bias_attr=None, act=None, gate_act=None, state_act=None,
                    name=None, state_name: Optional[str] = None,
                    layer_attr=None) -> LayerOutput:
    """One LSTM step for use inside recurrent_group (ref: LstmStepLayer.cpp):
    input is [B,4*size] pre-projected (incl. recurrent term), state is the
    previous cell memory.  Publishes the new cell state under `state_name` so
    a memory() can link to it."""
    name = _name(name, "lstm_step")
    cfg = LayerConfig(name=name, type="lstm_step", size=size,
                      active_type=act_name(act or TanhActivation()))
    cfg.attrs["active_gate_type"] = act_name(gate_act or SigmoidActivation())
    cfg.attrs["active_state_type"] = act_name(state_act or TanhActivation())
    cfg.attrs["state_name"] = state_name or f"{name}_state"
    cfg.inputs.append(LayerInput(input_layer_name=input.name))
    cfg.inputs.append(LayerInput(input_layer_name=state.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size * 7])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "lstm_step", size, parents=[input, state])


def gru_step_layer(input: LayerOutput, output_mem: LayerOutput, size: Optional[int] = None,
                   bias_attr=None, act=None, gate_act=None, name=None,
                   param_attr=None, layer_attr=None) -> LayerOutput:
    """One GRU step for use inside recurrent_group (ref: GruStepLayer.cpp):
    input is [B,3*size] pre-projected; output_mem the previous hidden; owns the
    recurrent weight [size, 3*size]."""
    size = size or input.size // 3
    name = _name(name, "gru_step")
    cfg = LayerConfig(name=name, type="gru_step", size=size,
                      active_type=act_name(act or TanhActivation()))
    cfg.attrs["active_gate_type"] = act_name(gate_act or SigmoidActivation())
    pname = _make_param(name, 0, [size, size * 3], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    cfg.inputs.append(LayerInput(input_layer_name=output_mem.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size * 3])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "gru_step", size, parents=[input, output_mem])


def recurrent_layer(input: LayerOutput, name=None, reverse: bool = False,
                    act=None, bias_attr=None, param_attr=None,
                    layer_attr=None) -> LayerOutput:
    """Vanilla RNN (ref: RecurrentLayer.cpp)."""
    size = input.size
    name = _name(name, "recurrent")
    cfg = LayerConfig(name=name, type="recurrent", size=size,
                      active_type=act_name(act or TanhActivation()), reversed=reverse)
    pname = _make_param(name, 0, [size, size], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "recurrent", size, parents=[input],
                       seq_level=input.seq_level)


# ---------------------------------------------------------------------------
# image layers
# ---------------------------------------------------------------------------

def img_conv_layer(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    act: Optional[BaseActivation] = None,
    groups: int = 1,
    stride: int = 1,
    padding: int = 0,
    bias_attr=None,
    param_attr: Optional[ParameterAttribute] = None,
    shared_biases: bool = True,
    layer_attr: Optional[ExtraLayerAttribute] = None,
    trans: bool = False,
) -> LayerOutput:
    """(ref: layers.py img_conv_layer; ExpandConvLayer.cpp)."""
    from paddle_tpu.graph.layers_conv import conv_output_size
    name = _name(name, "conv")
    if num_channels is None:
        num_channels = input.num_filters if input.num_filters else 1
    img = input.img_size if input.img_size else int(math.sqrt(input.size // num_channels))
    if not trans:
        out_x = conv_output_size(img, filter_size, stride, padding)
    else:
        # transposed conv output size: inverse of conv_output_size
        out_x = (img - 1) * stride - 2 * padding + filter_size
    conv = ConvConfig(filter_size=filter_size, channels=num_channels, stride=stride,
                      padding=padding, groups=groups, img_size=img, img_size_y=img,
                      output_x=out_x, output_y=out_x)
    size = num_filters * out_x * out_x
    cfg = LayerConfig(name=name, type="exconvt" if trans else "exconv", size=size,
                      active_type=act_name(act or TanhActivation()),
                      num_filters=num_filters, conv=conv, shared_biases=shared_biases)
    if param_attr is None:
        # reference conv init: std = sqrt(1 / (fan_in)) with fan_in = C/g*f*f
        param_attr = ParameterAttribute(
            initial_std=math.sqrt(1.0 / (num_channels // groups * filter_size * filter_size)))
    wdims = [num_filters, num_channels // groups * filter_size * filter_size]
    pname = _make_param(name, 0, wdims, param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    bias_dims = [1, num_filters] if shared_biases else [1, size]
    cfg.bias_parameter_name = _bias_name(name, bias_attr, bias_dims)
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, cfg.type, size, parents=[input],
                       num_filters=num_filters, img_size=out_x, img_size_y=out_x,
                       seq_level=input.seq_level)


def img_pool_layer(
    input: LayerOutput,
    pool_size: int,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    pool_type: Optional[BasePoolingType] = None,
    stride: int = 1,
    padding: int = 0,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """(ref: layers.py img_pool_layer; PoolLayer.cpp)."""
    from paddle_tpu.graph.layers_conv import conv_output_size
    name = _name(name, "pool")
    if num_channels is None:
        num_channels = input.num_filters
    img = input.img_size if input.img_size else int(math.sqrt(input.size // num_channels))
    ptype = "max-projection" if (pool_type is None or isinstance(pool_type, MaxPooling)) \
        else "avg-projection"
    out_x = conv_output_size(img, pool_size, stride, padding, caffe_mode=False)
    pool = PoolConfig(pool_type=ptype, channels=num_channels, size_x=pool_size,
                      stride=stride, padding=padding, img_size=img, img_size_y=img,
                      output_x=out_x, output_y=out_x)
    size = num_channels * out_x * out_x
    cfg = LayerConfig(name=name, type="pool", size=size, pool=pool)
    cfg.inputs.append(LayerInput(input_layer_name=input.name))
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "pool", size, parents=[input],
                       num_filters=num_channels, img_size=out_x, img_size_y=out_x,
                       seq_level=input.seq_level)


def img_cmrnorm_layer(input: LayerOutput, size: int = 5, scale: float = 0.0128,
                      power: float = 0.75, name=None, num_channels=None,
                      layer_attr=None) -> LayerOutput:
    """Cross-map response norm (ref: layers.py img_cmrnorm_layer;
    NormProjectionLayer.cpp)."""
    name = _name(name, "norm")
    if num_channels is None:
        num_channels = input.num_filters
    img = input.img_size if input.img_size else int(math.sqrt(input.size // num_channels))
    norm = NormConfig(norm_type="cmrnorm-projection", channels=num_channels,
                      size=size, scale=scale / size, pow=power, img_size=img,
                      img_size_y=img, output_x=img, output_y=img)
    cfg = LayerConfig(name=name, type="norm", size=input.size, norm=norm)
    cfg.inputs.append(LayerInput(input_layer_name=input.name))
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "norm", input.size, parents=[input],
                       num_filters=num_channels, img_size=img, img_size_y=img)


def batch_norm_layer(input: LayerOutput, act=None, name=None, num_channels=None,
                     bias_attr=None, param_attr=None, layer_attr=None,
                     use_global_stats=None,
                     moving_average_fraction: float = 0.9) -> LayerOutput:
    """(ref: layers.py batch_norm_layer; BatchNormalizationLayer.cpp).
    Moving mean/var are executor state, not parameters — the reference's
    static mean/var parameter pair collapses into the state dict."""
    name = _name(name, "batch_norm")
    img = 0
    if num_channels is None:
        num_channels = input.num_filters if input.num_filters else input.size
    if input.num_filters:
        img = input.img_size
    cfg = LayerConfig(name=name, type="batch_norm", size=input.size,
                      active_type=act_name(act or LinearActivation()),
                      use_global_stats=use_global_stats,
                      moving_average_fraction=moving_average_fraction)
    if img:
        cfg.conv = ConvConfig(channels=num_channels, img_size=img, img_size_y=img)
    if param_attr is None:
        param_attr = ParameterAttribute(initial_mean=1.0, initial_std=0.0)
        # scale starts at 1 (ref: BatchNormBaseLayer init)
    pa = ParameterConfig(name=f"_{name}.w0", size=num_channels, dims=[1, num_channels],
                         initial_strategy="zero", initial_mean=1.0, initial_std=0.0)
    pa.initial_strategy = "normal"
    if isinstance(param_attr, ParameterAttribute):
        param_attr.apply(pa)
    pa.initial_mean = 1.0 if pa.initial_mean == 0.0 else pa.initial_mean
    pa.initial_std = 0.0
    current_context().add_parameter(pa)
    cfg.inputs.append(LayerInput(input_layer_name=input.name,
                                 input_parameter_name=pa.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, num_channels])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "batch_norm", input.size, parents=[input],
                       num_filters=input.num_filters, img_size=input.img_size,
                       img_size_y=input.img_size_y, seq_level=input.seq_level)


def bilinear_interp_layer(input: LayerOutput, out_size_x: int, out_size_y: int,
                          name=None, layer_attr=None) -> LayerOutput:
    """(ref: BilinearInterpLayer.cpp)."""
    C = input.num_filters
    size = C * out_size_x * out_size_y
    out = _simple_layer("bilinear_interp", [input], size, name=name,
                        layer_attr=layer_attr,
                        cfg_extra={"channels": C, "img_size_x": input.img_size,
                                   "img_size_y": input.img_size_y or input.img_size,
                                   "out_size_x": out_size_x, "out_size_y": out_size_y})
    out.num_filters = C
    out.img_size = out_size_x
    out.img_size_y = out_size_y
    return out


def block_expand_layer(input: LayerOutput, block_x: int, block_y: int,
                       stride_x: int = 1, stride_y: int = 1,
                       padding_x: int = 0, padding_y: int = 0,
                       num_channels: Optional[int] = None, name=None,
                       layer_attr=None) -> LayerOutput:
    """im2col to sequence (ref: BlockExpandLayer.cpp)."""
    C = num_channels if num_channels else input.num_filters
    size = C * block_x * block_y
    out = _simple_layer(
        "blockexpand", [input], size, name=name, layer_attr=layer_attr,
        cfg_extra={"channels": C, "img_size_x": input.img_size,
                   "img_size_y": input.img_size_y or input.img_size,
                   "block_x": block_x, "block_y": block_y,
                   "stride_x": stride_x, "stride_y": stride_y,
                   "padding_x": padding_x, "padding_y": padding_y})
    out.seq_level = 1
    return out


def maxout_layer(input: LayerOutput, groups: int, num_channels=None, name=None,
                 layer_attr=None) -> LayerOutput:
    """(ref: MaxOutLayer.cpp)."""
    C = num_channels if num_channels else input.num_filters
    size = input.size // groups
    out = _simple_layer("maxout", [input], size, name=name, layer_attr=layer_attr,
                        cfg_extra={"groups": groups, "channels": C})
    out.num_filters = C // groups
    out.img_size = input.img_size
    out.img_size_y = input.img_size_y
    return out


def spp_layer(input: LayerOutput, pyramid_height: int, num_channels=None,
              pool_type=None, name=None, layer_attr=None) -> LayerOutput:
    """(ref: SpatialPyramidPoolLayer.cpp)."""
    C = num_channels if num_channels else input.num_filters
    img = input.img_size
    total = sum((2 ** l) * (2 ** l) for l in range(pyramid_height))
    ptype = "max-projection" if (pool_type is None or isinstance(pool_type, MaxPooling)) \
        else "avg-projection"
    name = _name(name, "spp")
    pool = PoolConfig(pool_type=ptype, channels=C, img_size=img, img_size_y=img)
    cfg = LayerConfig(name=name, type="spp", size=C * total, pool=pool)
    cfg.attrs["pyramid_height"] = pyramid_height
    cfg.inputs.append(LayerInput(input_layer_name=input.name))
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "spp", C * total, parents=[input])


def conv_shift_layer(a: LayerOutput, b: LayerOutput, name=None) -> LayerOutput:
    """Circular 1-D convolution of each row of a by kernel b
    (ref: ConvShiftLayer.cpp)."""
    return _simple_layer("conv_shift", [a, b], a.size, name=name,
                         prefix="conv_shift")


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

def multi_head_attention_layer(
    query: LayerOutput,
    key: Optional[LayerOutput] = None,
    value: Optional[LayerOutput] = None,
    *,
    size: int,
    num_heads: int,
    causal: bool = False,
    block_k: Optional[int] = None,
    block_k_min: Optional[int] = None,
    attn_impl: Optional[str] = None,
    num_kv_heads: Optional[int] = None,
    window: Optional[int] = None,
    use_rope: bool = False,
    rope_theta: float = 10000.0,
    name: Optional[str] = None,
    param_attr: Optional[Union[ParameterAttribute, list]] = None,
    bias_attr=False,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """Multi-head scaled-dot-product attention over padded sequences — NEW
    capability (the reference's closest analog is the additive-attention
    composite simple_attention, ref: networks.py:1257).  Self-attention when
    key/value are omitted.  Picks dense/flash(pallas)/blockwise/ring
    automatically (graph/layers_attn.py; attn_impl forces one of
    dense/flash/blockwise/ring/ulysses — 'ulysses' is the all-to-all
    context-parallel layout, needing a `seq` mesh axis and
    num_heads % seq_axis == 0); with a `seq`
    mesh axis the sequence is context-parallel via ring attention
    (parallel/context.py).

    param_attr: one attribute applied to all four projections (q/k/v/out), or
    a list of four.  A single NAMED attribute would tie all projections to
    one parameter, which is never what you want — pass a list instead."""
    key = key if key is not None else query
    value = value if value is not None else key
    assert size % num_heads == 0, "size must divide evenly into heads"
    if num_kv_heads is not None:
        assert num_kv_heads >= 1 and num_heads % num_kv_heads == 0, \
            f"num_kv_heads must be >= 1 and divide num_heads " \
            f"(got {num_kv_heads} for {num_heads} heads)"
    assert window is None or window >= 1, \
        f"window must be >= 1 (got {window}); window=0 would mask every key"
    assert not use_rope or (size // num_heads) % 2 == 0, \
        f"use_rope needs an even head dim (got size {size} / {num_heads} " \
        f"heads = {size // num_heads})"
    assert not use_rope or key is query, \
        "use_rope requires self-attention: rotating decoder queries and " \
        "unrelated encoder keys by their own arange positions imposes a " \
        "spurious relative-position bias in cross-attention"
    if isinstance(param_attr, ParameterAttribute):
        assert not param_attr.name, \
            "a single named param_attr would share ONE matrix across the " \
            "q/k/v/out projections; pass a list of 4 ParameterAttributes"
        attrs = [param_attr] * 4
    else:
        attrs = list(param_attr) if param_attr else [None] * 4
        assert len(attrs) == 4, "param_attr list must have 4 entries (q,k,v,out)"
    name = _name(name, "mha_layer")
    cfg = LayerConfig(name=name, type="multi_head_attention", size=size,
                      active_type="")
    cfg.attrs["num_heads"] = num_heads
    cfg.attrs["causal"] = causal
    if block_k is not None:          # key-block size (blockwise/flash paths)
        cfg.attrs["block_k"] = block_k
    if block_k_min is not None:      # min key length to leave the dense path
        cfg.attrs["block_k_min"] = block_k_min
    if attn_impl is not None:  # dense/flash/blockwise/ring/ulysses
        cfg.attrs["attn_impl"] = attn_impl
    if num_kv_heads is not None:     # grouped-query attention
        cfg.attrs["num_kv_heads"] = num_kv_heads
    if window is not None:           # sliding-window attention
        cfg.attrs["window"] = window
    if use_rope:                     # rotary position embeddings
        cfg.attrs["use_rope"] = True
        cfg.attrs["rope_theta"] = rope_theta
    kv_dim = size if num_kv_heads is None \
        else (size // num_heads) * num_kv_heads
    for i, (inp, dim_in, dim_out) in enumerate(
            [(query, query.size, size), (key, key.size, kv_dim),
             (value, value.size, kv_dim), (query, size, size)]):
        pname = _make_param(name, i, [dim_in, dim_out], attrs[i])
        cfg.inputs.append(LayerInput(input_layer_name=inp.name,
                                     input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "multi_head_attention", size,
                       parents=[query, key, value],
                       seq_level=query.seq_level)


def layer_norm_layer(
    input: LayerOutput,
    name: Optional[str] = None,
    param_attr: Optional[ParameterAttribute] = None,
    bias_attr=True,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """Last-dim layer normalization with learned scale/bias (beyond the
    reference's zoo — required by the transformer-era blocks; see
    graph/layers_misc.py layer_norm)."""
    name = _name(name, "layer_norm")
    cfg = LayerConfig(name=name, type="layer_norm", size=input.size,
                      active_type="")
    pa = param_attr or ParameterAttribute(initial_mean=1.0, initial_std=0.0)
    pname = _make_param(name, 0, [1, input.size], pa)
    cfg.inputs.append(LayerInput(input_layer_name=input.name,
                                 input_parameter_name=pname))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, input.size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "layer_norm", input.size, parents=[input],
                       seq_level=input.seq_level)


def moe_layer(
    input: LayerOutput,
    *,
    num_experts: int,
    expert_hidden: int,
    size: Optional[int] = None,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    aux_weight: float = 0.01,
    name: Optional[str] = None,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    """Mixture-of-experts FFN block — NEW capability (parallel/moe.py):
    top-k routed experts with capacity, load-balancing aux loss, expert
    weights sharded over the `model` mesh axis (expert parallelism).
    size defaults to the input width (residual-friendly)."""
    import math as _math
    size = size if size is not None else input.size
    name = _name(name, "moe_layer")
    D, E, H = input.size, num_experts, expert_hidden
    cfg = LayerConfig(name=name, type="moe", size=size, active_type="")
    cfg.attrs["top_k"] = top_k
    cfg.attrs["capacity_factor"] = capacity_factor
    cfg.attrs["aux_weight"] = aux_weight
    espec = ["model", None, None]
    specs = [
        ([D, E], ParameterAttribute(initial_std=1.0 / _math.sqrt(D))),
        ([E, D, H], ParameterAttribute(initial_std=1.0 / _math.sqrt(D),
                                       partition_spec=espec)),
        ([E, H], ParameterAttribute(initial_std=0.0, initial_mean=0.0,
                                    partition_spec=espec[:2])),
        ([E, H, size], ParameterAttribute(initial_std=1.0 / _math.sqrt(H),
                                          partition_spec=espec)),
        ([E, size], ParameterAttribute(initial_std=0.0, initial_mean=0.0,
                                       partition_spec=espec[:2])),
    ]
    for i, (dims, attr) in enumerate(specs):
        pname = _make_param(name, i, dims, attr)
        cfg.inputs.append(LayerInput(input_layer_name=input.name,
                                     input_parameter_name=pname))
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "moe", size, parents=[input],
                       seq_level=input.seq_level)


# ---------------------------------------------------------------------------
# id/decision layers
# ---------------------------------------------------------------------------

def maxid_layer(input: LayerOutput, name=None, beam_size: int = 0,
                layer_attr=None) -> LayerOutput:
    """(ref: MaxIdLayer.cpp)."""
    return _simple_layer("maxid", [input], input.size, name=name,
                         layer_attr=layer_attr, cfg_extra={"beam_size": beam_size},
                         prefix="maxid")


def sampling_id_layer(input: LayerOutput, name=None, layer_attr=None) -> LayerOutput:
    """(ref: SamplingIdLayer.cpp)."""
    return _simple_layer("sampling_id", [input], input.size, name=name,
                         layer_attr=layer_attr, prefix="sampling_id")


def eos_layer(input: LayerOutput, eos_id: int, name=None, layer_attr=None) -> LayerOutput:
    """(ref: EosIdCheckLayer.cpp)."""
    return _simple_layer("eos_id", [input], 1, name=name, layer_attr=layer_attr,
                         cfg_extra={"eos_id": eos_id}, prefix="eos")


# ---------------------------------------------------------------------------
# elementwise / comparison layers
# ---------------------------------------------------------------------------

def cos_sim(a: LayerOutput, b: LayerOutput, scale: float = 1.0, name=None,
            layer_attr=None) -> LayerOutput:
    """(ref: CosSimLayer.cpp)."""
    return _simple_layer("cos", [a, b], 1, name=name, layer_attr=layer_attr,
                         cfg_extra={"cos_scale": scale}, prefix="cos_sim")


def cos_sim_vecmat(v: LayerOutput, m: LayerOutput, size: int, scale: float = 1.0,
                   name=None) -> LayerOutput:
    """(ref: CosSimVecMatLayer.cpp)."""
    return _simple_layer("cos_vm", [v, m], size, name=name,
                         cfg_extra={"cos_scale": scale}, prefix="cos_vm")


def trans_layer(input: LayerOutput, name=None) -> LayerOutput:
    """(ref: TransLayer.cpp)."""
    return _simple_layer("trans", [input], input.size, name=name, prefix="trans")


def resize_layer(input: LayerOutput, size: int, name=None) -> LayerOutput:
    """(ref: ResizeLayer.cpp)."""
    return _simple_layer("resize", [input], size, name=name, prefix="resize")


def slope_intercept_layer(input: LayerOutput, slope: float = 1.0,
                          intercept: float = 0.0, name=None) -> LayerOutput:
    """(ref: SlopeInterceptLayer.cpp)."""
    return _simple_layer("slope_intercept", [input], input.size, name=name,
                         cfg_extra={"slope": slope, "intercept": intercept},
                         prefix="slope_intercept")


def scaling_layer(weight: LayerOutput, input: LayerOutput, name=None) -> LayerOutput:
    """(ref: ScalingLayer.cpp): input0 = [B,1] weights, input1 = values."""
    return _simple_layer("scaling", [weight, input], input.size, name=name,
                         prefix="scaling")


def interpolation_layer(weight: LayerOutput, a: LayerOutput, b: LayerOutput,
                        name=None) -> LayerOutput:
    """(ref: InterpolationLayer.cpp)."""
    return _simple_layer("interpolation", [weight, a, b], a.size, name=name,
                         prefix="interpolation")


def power_layer(weight: LayerOutput, input: LayerOutput, name=None) -> LayerOutput:
    """(ref: PowerLayer.cpp)."""
    return _simple_layer("power", [weight, input], input.size, name=name,
                         prefix="power")


def linear_comb_layer(weights: LayerOutput, vectors: LayerOutput, size: int,
                      name=None) -> LayerOutput:
    """(ref: ConvexCombinationLayer.cpp)."""
    return _simple_layer("convex_comb", [weights, vectors], size, name=name,
                         prefix="linear_comb")


convex_comb_layer = linear_comb_layer


def outer_prod_layer(a: LayerOutput, b: LayerOutput, name=None) -> LayerOutput:
    """(ref: OuterProdLayer.cpp)."""
    return _simple_layer("out_prod", [a, b], a.size * b.size, name=name,
                         prefix="out_prod")


def tensor_layer(a: LayerOutput, b: LayerOutput, size: int, act=None, name=None,
                 param_attr=None, bias_attr=None, layer_attr=None) -> LayerOutput:
    """(ref: TensorLayer.cpp)."""
    name = _name(name, "tensor")
    cfg = LayerConfig(name=name, type="tensor", size=size,
                      active_type=act_name(act or LinearActivation()))
    pname = _make_param(name, 0, [a.size, size * b.size], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=a.name, input_parameter_name=pname))
    cfg.inputs.append(LayerInput(input_layer_name=b.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "tensor", size, parents=[a, b])


def multiplex_layer(index: LayerOutput, inputs: Sequence[LayerOutput],
                    name=None) -> LayerOutput:
    """(ref: MultiplexLayer.cpp)."""
    ins = [index] + list(inputs)
    return _simple_layer("multiplex", ins, inputs[0].size, name=name,
                         prefix="multiplex")


def selective_fc_layer(input, select: Optional[LayerOutput], size: int, act=None,
                       name=None, param_attr=None, bias_attr=None,
                       layer_attr=None) -> LayerOutput:
    """(ref: SelectiveFullyConnectedLayer.cpp)."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    name = _name(name, "selective_fc")
    cfg = LayerConfig(name=name, type="selective_fc", size=size,
                      active_type=act_name(act or TanhActivation()))
    attrs = param_attr if isinstance(param_attr, list) else [param_attr] * len(inputs)
    for i, (inp, pa) in enumerate(zip(inputs, attrs)):
        pname = _make_param(name, i, [inp.size, size], pa)
        cfg.inputs.append(LayerInput(input_layer_name=inp.name, input_parameter_name=pname))
    if select is not None:
        cfg.inputs.append(LayerInput(input_layer_name=select.name))
        cfg.attrs["has_selected_colums"] = True
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, size])
    _layer_attr_fields(cfg, layer_attr)
    current_context().add_layer(cfg)
    return LayerOutput(name, "selective_fc", size, parents=inputs)


def print_layer(input: LayerOutput, name=None) -> LayerOutput:
    """(ref: PrintLayer.cpp)."""
    return _simple_layer("print", [input], input.size, name=name, prefix="print")


# ---------------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------------

def _cost_layer(type_: str, inputs: list[LayerOutput], name, coeff: float = 1.0,
                cfg_extra: Optional[dict] = None, prefix: str = "cost",
                params: Optional[list] = None, size: int = 1) -> LayerOutput:
    name = _name(name, prefix)
    cfg = LayerConfig(name=name, type=type_, size=size, coeff=coeff)
    for i, inp in enumerate(inputs):
        li = LayerInput(input_layer_name=inp.name)
        if params and params[i] is not None:
            li.input_parameter_name = _make_param(name, i, params[i][0], params[i][1])
        cfg.inputs.append(li)
    if cfg_extra:
        for k, v in cfg_extra.items():
            if hasattr(cfg, k) and k != "attrs":
                setattr(cfg, k, v)
            else:
                cfg.attrs[k] = v
    current_context().add_layer(cfg)
    current_context().model.output_layer_names.append(name)
    return LayerOutput(name, type_, size, parents=inputs)


def classification_cost(input: LayerOutput, label: LayerOutput, weight=None,
                        name=None, evaluator=None, coeff: float = 1.0) -> LayerOutput:
    """Softmax classification cost + classification_error evaluator
    (ref: layers.py classification_cost — attaches default evaluators)."""
    inputs = [input, label] + ([weight] if weight is not None else [])
    out = _cost_layer("multi-class-cross-entropy", inputs, name, coeff,
                      prefix="classification_cost")
    current_context().add_evaluator(EvaluatorConfig(
        name=f"{out.name}.classification_error", type="classification_error",
        input_layer_names=[input.name, label.name]))
    return out


def regression_cost(input: LayerOutput, label: LayerOutput, weight=None,
                    name=None, coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py regression_cost — sum of squares)."""
    inputs = [input, label] + ([weight] if weight is not None else [])
    return _cost_layer("square_error", inputs, name, coeff, prefix="regression_cost")


def cross_entropy(input: LayerOutput, label: LayerOutput, name=None,
                  coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py cross_entropy)."""
    return _cost_layer("multi-class-cross-entropy", [input, label], name, coeff)


def cross_entropy_with_selfnorm(input: LayerOutput, label: LayerOutput, name=None,
                                coeff: float = 1.0,
                                softmax_selfnorm_alpha: float = 0.1) -> LayerOutput:
    """(ref: layers.py cross_entropy_with_selfnorm)."""
    return _cost_layer("multi_class_cross_entropy_with_selfnorm", [input, label],
                       name, coeff,
                       cfg_extra={"softmax_selfnorm_alpha": softmax_selfnorm_alpha})


def soft_binary_class_cross_entropy(input: LayerOutput, label: LayerOutput,
                                    name=None, coeff: float = 1.0) -> LayerOutput:
    return _cost_layer("soft_binary_class_cross_entropy", [input, label], name, coeff)


def multi_binary_label_cross_entropy(input: LayerOutput, label: LayerOutput,
                                     name=None, coeff: float = 1.0) -> LayerOutput:
    return _cost_layer("multi_binary_label_cross_entropy", [input, label], name, coeff)


def rank_cost(left: LayerOutput, right: LayerOutput, label: LayerOutput,
              weight=None, name=None, coeff: float = 1.0) -> LayerOutput:
    """(ref: RankingCost)."""
    inputs = [left, right, label] + ([weight] if weight is not None else [])
    return _cost_layer("rank-cost", inputs, name, coeff)


def lambda_cost(input: LayerOutput, score: LayerOutput, name=None,
                NDCG_num: int = 5, max_sort_size: int = -1,
                coeff: float = 1.0) -> LayerOutput:
    """(ref: LambdaCost)."""
    return _cost_layer("lambda_cost", [input, score], name, coeff,
                       cfg_extra={"NDCG_num": NDCG_num, "max_sort_size": max_sort_size})


def huber_cost(input: LayerOutput, label: LayerOutput, name=None,
               coeff: float = 1.0) -> LayerOutput:
    """(ref: HuberTwoClass)."""
    return _cost_layer("huber_classification", [input, label], name, coeff)


def sum_cost(input: LayerOutput, name=None, coeff: float = 1.0) -> LayerOutput:
    """Sum the input as a cost (ref: SumCostLayer)."""
    return _cost_layer("sum_cost", [input], name, coeff)


def auc_validation(input: LayerOutput, label: LayerOutput, weight=None,
                   name=None) -> LayerOutput:
    """In-graph AUC during training (ref: AucValidation —
    config_parser.py:1961, ValidationLayer.cpp): pass-through layer whose
    (score, label[, weight]) inputs feed a last-column-auc evaluator."""
    inputs = [input, label] + ([weight] if weight is not None else [])
    return _cost_layer("auc-validation", inputs, name, prefix="auc_validation",
                       size=input.size)


def pnpair_validation(input: LayerOutput, label: LayerOutput,
                      info: LayerOutput, weight=None, name=None) -> LayerOutput:
    """In-graph positive-negative pair rate (ref: PnpairValidation —
    config_parser.py:1962, ValidationLayer.cpp): (score, label, query-info
    [, weight]) feed a pnpair evaluator grouping by info id."""
    inputs = [input, label, info] + ([weight] if weight is not None else [])
    return _cost_layer("pnpair-validation", inputs, name,
                       prefix="pnpair_validation", size=input.size)


def crf_layer(input: LayerOutput, label: LayerOutput, size: Optional[int] = None,
              weight=None, param_attr=None, name=None,
              coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py crf_layer; CRFLayer.cpp; parameter [(C+2), C])."""
    size = size or input.size
    inputs = [input, label] + ([weight] if weight is not None else [])
    params = [([size + 2, size], param_attr)] + [None] * (len(inputs) - 1)
    out = _cost_layer("crf", inputs, name, coeff, prefix="crf",
                      cfg_extra={"num_classes": size}, params=params)
    out.size = size
    return out


def crf_decoding_layer(input: LayerOutput, size: Optional[int] = None,
                       label: Optional[LayerOutput] = None, param_attr=None,
                       name=None) -> LayerOutput:
    """(ref: CRFDecodingLayer.cpp)."""
    size = size or input.size
    inputs = [input] + ([label] if label is not None else [])
    name = _name(name, "crf_decoding")
    cfg = LayerConfig(name=name, type="crf_decoding", size=size, num_classes=size)
    pname = _make_param(name, 0, [size + 2, size], param_attr)
    cfg.inputs.append(LayerInput(input_layer_name=input.name, input_parameter_name=pname))
    if label is not None:
        cfg.inputs.append(LayerInput(input_layer_name=label.name))
    current_context().add_layer(cfg)
    return LayerOutput(name, "crf_decoding", size, parents=inputs, seq_level=input.seq_level)


def ctc_layer(input: LayerOutput, label: LayerOutput, size: Optional[int] = None,
              name=None, norm_by_times: bool = False, blank: Optional[int] = None,
              coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py ctc_layer; CTCLayer.cpp — blank defaults to size-1)."""
    size = size or input.size
    return _cost_layer("ctc", [input, label], name, coeff, prefix="ctc",
                       cfg_extra={"blank": blank if blank is not None else size - 1,
                                  "norm_by_times": norm_by_times})


def nce_layer(input, label: LayerOutput, num_classes: int,
              num_neg_samples: int = 10, neg_distribution: Optional[list] = None,
              weight=None, name=None, param_attr=None, bias_attr=None,
              coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py nce_layer; NCELayer.cpp)."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    name = _name(name, "nce")
    cfg = LayerConfig(name=name, type="nce", size=1, coeff=coeff,
                      num_classes=num_classes, num_neg_samples=num_neg_samples)
    if neg_distribution is not None:
        cfg.neg_sampling_dist = list(neg_distribution)
    attrs = param_attr if isinstance(param_attr, list) else [param_attr] * len(inputs)
    for i, (inp, pa) in enumerate(zip(inputs, attrs)):
        pname = _make_param(name, i, [num_classes, inp.size], pa)
        cfg.inputs.append(LayerInput(input_layer_name=inp.name, input_parameter_name=pname))
    cfg.inputs.append(LayerInput(input_layer_name=label.name))
    if weight is not None:
        cfg.inputs.append(LayerInput(input_layer_name=weight.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, num_classes])
    current_context().add_layer(cfg)
    current_context().model.output_layer_names.append(name)
    return LayerOutput(name, "nce", 1, parents=inputs)


def hsigmoid(input, label: LayerOutput, num_classes: int, name=None,
             param_attr=None, bias_attr=None, coeff: float = 1.0) -> LayerOutput:
    """(ref: layers.py hsigmoid; HierarchicalSigmoidLayer.cpp)."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    name = _name(name, "hsigmoid")
    cfg = LayerConfig(name=name, type="hsigmoid", size=1, coeff=coeff,
                      num_classes=num_classes)
    attrs = param_attr if isinstance(param_attr, list) else [param_attr] * len(inputs)
    for i, (inp, pa) in enumerate(zip(inputs, attrs)):
        pname = _make_param(name, i, [num_classes - 1, inp.size], pa)
        cfg.inputs.append(LayerInput(input_layer_name=inp.name, input_parameter_name=pname))
    cfg.inputs.append(LayerInput(input_layer_name=label.name))
    cfg.bias_parameter_name = _bias_name(name, bias_attr, [1, num_classes - 1])
    current_context().add_layer(cfg)
    current_context().model.output_layer_names.append(name)
    return LayerOutput(name, "hsigmoid", 1, parents=inputs)


# ---------------------------------------------------------------------------
# recurrent groups & generation
# ---------------------------------------------------------------------------

class StaticInput:
    """Non-sequence input broadcast to every step of a recurrent group
    (ref: layers.py StaticInput)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False, size: Optional[int] = None):
        self.input = input
        self.is_seq = is_seq
        self.size = size or input.size


class SubsequenceInput:
    """Marks a recurrent_group in-link as a nested (level-2) sequence: the
    group steps over SUB-SEQUENCES, feeding each step a full [B, T, ...]
    sequence — the hierarchical-RNN form (ref: layers.py SubsequenceInput;
    RecurrentGradientMachine.cpp:626-699)."""

    def __init__(self, input: LayerOutput):
        self.input = input


class BaseGeneratedInput:
    """Base of generation feedback inputs (ref: layers.py
    BaseGeneratedInput:2939) — user code subclasses it to customize the
    feedback path of beam search."""


class GeneratedInput(BaseGeneratedInput):
    """Feedback input for generation: embedding of the previously generated
    token (ref: layers.py GeneratedInput)."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size                  # vocabulary size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def memory(name: Optional[str], size: int, is_seq: bool = False,
           boot_layer: Optional[LayerOutput] = None, boot_bias=None,
           boot_bias_active_type=None,
           boot_with_const_id: Optional[int] = None) -> LayerOutput:
    """Read `name`'s output from the previous timestep
    (ref: layers.py memory:2444; config_parser.py Memory).

    Must be called inside a recurrent_group step function.  Creates an agent
    layer fed by the scan carry; registers a MemoryConfig on the group.
    """
    ctx = current_context()
    recurrent = [g for g in ctx.group_stack if g.is_recurrent_layer_group]
    assert recurrent, ("memory() must be used inside recurrent_group "
                       "(a sub_network scope is not a recurrent group)")
    sm = recurrent[-1]
    agent_name = ctx.unique_name(f"memory_{name or 'anon'}")
    cfg = LayerConfig(name=agent_name, type="agent", size=size)
    ctx.add_layer(cfg)
    mem = MemoryConfig(
        link_name=name or "", layer_name=agent_name, size=size,
        boot_layer_name=boot_layer.name if boot_layer is not None else "",
        boot_with_const_id=boot_with_const_id, is_sequence=is_seq)
    sm.memories.append(mem)
    return LayerOutput(agent_name, "agent", size)


def recurrent_group(step, input, reverse: bool = False,
                    name: Optional[str] = None):
    """Run `step` over every timestep of the input sequence(s)
    (ref: layers.py recurrent_group:2786; RecurrentGradientMachine).

    `input`: LayerOutput (sequence in-link), StaticInput, or a list of them.
    Returns the step function's output as a sequence LayerOutput (or a list).
    """
    ctx = current_context()
    name = _name(name, "recurrent_group")
    inputs = input if isinstance(input, (list, tuple)) else [input]

    sm = SubModelConfig(name=name, is_recurrent_layer_group=True, reversed=reverse)
    recurrent_ancestors = [g for g in ctx.group_stack
                           if g.is_recurrent_layer_group]
    if recurrent_ancestors:
        # nested group: executed inside the enclosing group's scan step
        # (a non-recurrent sub_network scope is bookkeeping, not execution)
        sm.parent = recurrent_ancestors[-1].name
    ctx.model.sub_models.append(sm)
    ctx.group_stack.append(sm)
    try:
        step_args = []
        gen_inputs = []
        for inp in inputs:
            if isinstance(inp, SubsequenceInput):
                # nested in-link: each step receives one whole subsequence
                src = inp.input
                alias = ctx.unique_name(f"inlink_{src.name}")
                ctx.add_layer(LayerConfig(name=alias, type="scatter_agent",
                                          size=src.size))
                sm.in_links.append(src.name)
                sm.in_link_layers.append(alias)
                step_args.append(LayerOutput(alias, "scatter_agent", src.size,
                                             seq_level=1))
            elif isinstance(inp, LayerOutput):
                # sequence in-link -> in-group alias (per-step slice)
                alias = ctx.unique_name(f"inlink_{inp.name}")
                ctx.add_layer(LayerConfig(name=alias, type="scatter_agent", size=inp.size))
                sm.in_links.append(inp.name)
                sm.in_link_layers.append(alias)
                step_args.append(LayerOutput(alias, "scatter_agent", inp.size,
                                             seq_level=max(inp.seq_level - 1, 0)))
            elif isinstance(inp, StaticInput):
                alias = ctx.unique_name(f"static_{inp.input.name}")
                ctx.add_layer(LayerConfig(name=alias, type="agent", size=inp.size))
                sm.static_links.append(inp.input.name)
                sm.static_link_layers.append(alias)
                step_args.append(LayerOutput(alias, "agent", inp.size,
                                             seq_level=1 if inp.is_seq else 0))
            elif isinstance(inp, GeneratedInput):
                gen_inputs.append(inp)
                # previous-token id memory + embedding lookup
                id_mem = memory(name=None, size=inp.size, boot_with_const_id=0)
                sm.memories[-1].link_name = "__generated_id__"  # patched by beam_search
                emb = embedding_layer(
                    input=LayerOutput(id_mem.name, "agent", inp.size),
                    size=inp.embedding_size,
                    param_attr=ParameterAttribute(name=inp.embedding_name),
                    name=ctx.unique_name("gen_emb"))
                sm.generator = GeneratorConfig(id_memory_layer_name=id_mem.name)
                step_args.append(emb)
            else:
                raise TypeError(f"bad recurrent_group input: {type(inp)}")

        outs = step(*step_args)
        out_list = outs if isinstance(outs, (list, tuple)) else [outs]
        for o in out_list:
            sm.output_layer_names.append(o.name)
    finally:
        ctx.group_stack.pop()

    results = [LayerOutput(o.name, o.layer_type, o.size, seq_level=1)
               for o in out_list]
    return results if isinstance(outs, (list, tuple)) else results[0]


class sub_network:
    """Scope layers into a named sub-network — the MultiNetwork / multi_nn
    analog (ref: gserver/gradientmachines/MultiNetwork.h:25-62).

    The reference runs each sub-network's forward/backward separately and
    sums the costs; here all sub-networks compile into the ONE jitted
    program (XLA schedules independent subgraphs concurrently — the correct
    TPU collapse of the sub-machine loop), so this scope is structural
    metadata: it groups layers in the config for tooling
    (dump_config/show_model) and marks the model type multi_nn.  Use one
    `with sub_network("task_a"): ...` block per task; costs from every
    block train jointly.
    """

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        ctx = current_context()
        sm = SubModelConfig(name=self.name, is_recurrent_layer_group=False)
        ctx.model.sub_models.append(sm)
        ctx.group_stack.append(sm)
        ctx.model.type = "multi_nn"
        self.sm = sm
        return self

    def __exit__(self, *exc):
        current_context().group_stack.pop()
        return False


def beam_search(step, input, bos_id: int, eos_id: int, beam_size: int,
                max_length: int = 100, name: Optional[str] = None,
                num_results_per_sample: Optional[int] = None) -> LayerOutput:
    """Sequence generation by beam search over a recurrent group
    (ref: layers.py beam_search:3087; RecurrentGradientMachine::beamSearch).

    `step` receives the group's per-step inputs (including the GeneratedInput
    embedding) and must return the next-token probability layer.
    """
    ctx = current_context()
    name = _name(name, "beam_search")

    prob_holder: list[LayerOutput] = []

    def wrapped_step(*args):
        out = step(*args)
        prob_holder.append(out)
        return out

    out = recurrent_group(step=wrapped_step, input=input, name=name)
    sm = ctx.model.sub_models[-1]
    assert sm.name == name
    gen = sm.generator or GeneratorConfig()
    gen.beam_size = beam_size
    gen.eos_id = eos_id
    gen.bos_id = bos_id
    gen.max_num_frames = max_length
    gen.num_results_per_sample = num_results_per_sample or beam_size
    gen.prob_layer_name = prob_holder[0].name
    # the generated-id memory feeds back the chosen token
    for mem in sm.memories:
        if mem.link_name == "__generated_id__":
            mem.link_name = gen.prob_layer_name   # executor reads argmax of probs
            mem.boot_with_const_id = bos_id
    sm.generator = gen
    ctx.model.type = "recurrent_nn"
    return out


def get_output_layer(input: LayerOutput, arg_name: str = "", name=None) -> LayerOutput:
    """(ref: GetOutputLayer.cpp)."""
    return _simple_layer("get_output", [input], input.size, name=name,
                         prefix="get_output")


# ---------------------------------------------------------------------------
# reference compat surface: level constants, type-name registry, bases
# ---------------------------------------------------------------------------

class AggregateLevel:
    """Pooling aggregation level constants (ref: layers.py
    AggregateLevel:204) — EACH_TIMESTEP pools a sequence to one vector,
    EACH_SEQUENCE pools a nested sequence to one vector per sub-sequence."""
    EACH_TIMESTEP = "non-seq"
    EACH_SEQUENCE = "seq"


class ExpandLevel:
    """Expansion level constants (ref: layers.py ExpandLevel:1292)."""
    FROM_TIMESTEP = AggregateLevel.EACH_TIMESTEP
    FROM_SEQUENCE = AggregateLevel.EACH_SEQUENCE


class LayerType:
    """Registered layer type-name constants (ref: layers.py LayerType:112).
    The authoritative registry is graph/registry.py; this mirror exists for
    configs that reference LayerType.X symbolically."""
    DATA = "data"
    FC_LAYER = "fc"
    MIXED_LAYER = "mixed"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    POOL_LAYER = "pool"
    BATCH_NORM_LAYER = "batch_norm"
    CONV_LAYER = "exconv"
    CONCAT_LAYER = "concat"
    ADDTO_LAYER = "addto"
    EMBEDDING_LAYER = "embedding"
    COST = "multi-class-cross-entropy"

    @classmethod
    def is_layer_type(cls, type_name: str) -> bool:
        """True for any of this class's constants (the reference's
        semantics) or any registered graph layer type."""
        consts = {v for k, v in vars(cls).items()
                  if k.isupper() and isinstance(v, str)}
        if type_name in consts:
            return True
        from paddle_tpu.graph.registry import layer_registry
        return type_name in layer_registry


def out_prod_layer(input1: LayerOutput, input2: LayerOutput, name=None,
                   layer_attr=None) -> LayerOutput:
    """Flattened outer product of two vectors (ref: layers.py
    out_prod_layer; OuterProdLayer.cpp)."""
    return _simple_layer("out_prod", [input1, input2],
                         input1.size * input2.size, name=name,
                         layer_attr=layer_attr, prefix="out_prod")


def sum_to_one_norm_layer(input: LayerOutput, name=None,
                          layer_attr=None) -> LayerOutput:
    """Row-normalize to sum 1 (ref: layers.py sum_to_one_norm_layer;
    SumToOneNormLayer.cpp)."""
    return _simple_layer("sum_to_one_norm", [input], input.size, name=name,
                         layer_attr=layer_attr, prefix="sum_to_one_norm")
