"""Pooling descriptors (ref: trainer_config_helpers/poolings.py)."""

from __future__ import annotations

__all__ = ["BasePoolingType", "MaxPooling", "AvgPooling", "SumPooling",
           "SquareRootNPooling", "LastPooling", "FirstPooling",
           "MaxWithIdPooling", "CudnnMaxPooling", "CudnnAvgPooling"]


class BasePoolingType:
    name: str = ""


class MaxPooling(BasePoolingType):
    name = "max"


class MaxWithIdPooling(BasePoolingType):
    name = "max"


class AvgPooling(BasePoolingType):
    name = "average"
    strategy = "average"


class SumPooling(BasePoolingType):
    name = "average"
    strategy = "sum"


class SquareRootNPooling(BasePoolingType):
    name = "average"
    strategy = "squarerootn"


class LastPooling(BasePoolingType):
    name = "seqlastins"


class FirstPooling(BasePoolingType):
    name = "seqlastins"
    select_first = True


# cuDNN-dispatch aliases (ref: poolings.py CudnnMaxPooling/CudnnAvgPooling)
# — the CPU-vs-cuDNN dispatch distinction is meaningless under XLA; the
# math is identical, so these are pure aliases
CudnnMaxPooling = MaxPooling
CudnnAvgPooling = AvgPooling
