"""Composite network builders
(ref: trainer_config_helpers/networks.py: simple_img_conv_pool:145,
small_vgg:418, vgg_16_network:448, simple_lstm:531, lstmemory_group:726,
simple_gru:937, bidirectional_lstm:1166, simple_attention:1257,
inputs/outputs:1376-1394)."""

from __future__ import annotations

from typing import Optional, Sequence

from paddle_tpu.dsl.activations import (
    BaseActivation, LinearActivation, ReluActivation, SequenceSoftmaxActivation,
    SigmoidActivation, SoftmaxActivation, TanhActivation,
)
from paddle_tpu.dsl.attrs import ExtraLayerAttribute, ParameterAttribute
from paddle_tpu.dsl.base import LayerOutput, current_context
from paddle_tpu.dsl.layers import (
    StaticInput, batch_norm_layer, concat_layer, context_projection,
    dropout_layer, expand_layer, fc_layer, first_seq, full_matrix_projection,
    gru_step_layer, grumemory, identity_projection, img_cmrnorm_layer,
    img_conv_layer, img_pool_layer, last_seq, lstm_step_layer, lstmemory,
    memory, mixed_layer, pooling_layer, recurrent_group, tensor_layer,
)
from paddle_tpu.dsl.poolings import MaxPooling

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "small_vgg", "vgg_16_network",
    "simple_lstm", "sequence_conv_pool", "lstmemory_group", "simple_gru", "gru_group",
    "bidirectional_lstm", "simple_attention", "inputs", "outputs",
    "lstmemory_unit", "gru_unit", "simple_gru2", "bidirectional_gru",
    "img_conv_bn_pool", "text_conv_pool",
]


def simple_img_conv_pool(input: LayerOutput, filter_size: int, num_filters: int,
                         pool_size: int, name: Optional[str] = None,
                         pool_type=None, act=None, groups: int = 1,
                         conv_stride: int = 1, conv_padding: int = 0,
                         bias_attr=None, num_channel: Optional[int] = None,
                         param_attr=None, shared_bias: bool = True,
                         conv_layer_attr=None, pool_stride: int = 1,
                         pool_padding: int = 0, pool_layer_attr=None) -> LayerOutput:
    """(ref: networks.py simple_img_conv_pool:145)."""
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, act=act, groups=groups, stride=conv_stride,
        padding=conv_padding, bias_attr=bias_attr, param_attr=param_attr,
        shared_biases=shared_bias, layer_attr=conv_layer_attr,
        name=f"{name}_conv" if name else None)
    return img_pool_layer(
        input=conv, pool_size=pool_size, pool_type=pool_type, stride=pool_stride,
        padding=pool_padding, layer_attr=pool_layer_attr,
        name=f"{name}_pool" if name else None)


def img_conv_group(input: LayerOutput, conv_num_filter: Sequence[int],
                   pool_size: int, num_channels: Optional[int] = None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride: int = 2, pool_type=None) -> LayerOutput:
    """Stack of convs followed by one pool (ref: networks.py img_conv_group)."""
    n = len(conv_num_filter)

    def as_list(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    paddings = as_list(conv_padding)
    fsizes = as_list(conv_filter_size)
    acts = conv_act if isinstance(conv_act, (list, tuple)) else [conv_act] * n
    bns = as_list(conv_with_batchnorm)
    drops = as_list(conv_batchnorm_drop_rate)

    tmp = input
    channels = num_channels
    for i in range(n):
        act = acts[i] or ReluActivation()
        tmp = img_conv_layer(
            input=tmp, filter_size=fsizes[i], num_filters=conv_num_filter[i],
            num_channels=channels, padding=paddings[i],
            act=LinearActivation() if bns[i] else act)
        channels = None
        if bns[i]:
            tmp = batch_norm_layer(
                input=tmp, act=act,
                layer_attr=ExtraLayerAttribute(drop_rate=drops[i]))
    return img_pool_layer(input=tmp, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type or MaxPooling())


def small_vgg(input_image: LayerOutput, num_channels: int, num_classes: int) -> LayerOutput:
    """The CIFAR VGG of the demos (ref: networks.py small_vgg:418 — four
    conv groups [64x2, 128x2, 256x3, 512x3] + 2 fc)."""
    def group(ipt, num_filter, times, channels=None):
        return img_conv_group(
            input=ipt, conv_num_filter=[num_filter] * times, pool_size=2,
            num_channels=channels, conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=0.0, pool_stride=2)

    tmp = group(input_image, 64, 2, num_channels)
    tmp = group(tmp, 128, 2)
    tmp = group(tmp, 256, 3)
    tmp = group(tmp, 512, 3)
    tmp = img_pool_layer(input=tmp, pool_size=8, stride=8)
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=512, act=LinearActivation())
    tmp = batch_norm_layer(input=tmp, act=ReluActivation(),
                           layer_attr=ExtraLayerAttribute(drop_rate=0.5))
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def vgg_16_network(input_image: LayerOutput, num_channels: int,
                   num_classes: int = 1000) -> LayerOutput:
    """Full VGG-16 (ref: networks.py vgg_16_network:448)."""
    def group(ipt, num_filter, times, channels=None):
        return img_conv_group(
            input=ipt, conv_num_filter=[num_filter] * times, pool_size=2,
            num_channels=channels, pool_stride=2)

    tmp = group(input_image, 64, 2, num_channels)
    tmp = group(tmp, 128, 2)
    tmp = group(tmp, 256, 3)
    tmp = group(tmp, 512, 3)
    tmp = group(tmp, 512, 3)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraLayerAttribute(drop_rate=0.5))
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraLayerAttribute(drop_rate=0.5))
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def sequence_conv_pool(input: LayerOutput, context_len: int, hidden_size: int,
                       name: Optional[str] = None,
                       context_start: Optional[int] = None, pool_type=None,
                       context_proj_param_attr=None, fc_param_attr=None,
                       fc_bias_attr=None, fc_act=None,
                       pool_bias_attr=None) -> LayerOutput:
    """Text conv pooling: context projection -> fc -> pooling
    (ref: networks.py sequence_conv_pool:41)."""
    with mixed_layer(name=f"{name}_conv_proj" if name else None,
                     size=input.size * context_len,
                     act=LinearActivation(), bias_attr=False) as m:
        m += context_projection(input, context_len=context_len,
                                context_start=context_start,
                                padding_attr=context_proj_param_attr or False)
    fc = fc_layer(input=m, size=hidden_size, act=fc_act,
                  param_attr=fc_param_attr, bias_attr=fc_bias_attr)
    return pooling_layer(input=fc, pooling_type=pool_type or MaxPooling(),
                         name=name, bias_attr=pool_bias_attr or False)


def simple_lstm(input: LayerOutput, size: int, name: Optional[str] = None,
                reverse: bool = False, mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None, act=None, gate_act=None, state_act=None,
                mixed_layer_attr=None, lstm_cell_attr=None) -> LayerOutput:
    """fc(4*size) + lstmemory (ref: networks.py simple_lstm:531)."""
    fc_name = f"{name}_transform" if name else None
    with mixed_layer(name=fc_name, size=size * 4, act=LinearActivation(),
                     bias_attr=False, layer_attr=mixed_layer_attr) as m:
        m += full_matrix_projection(input, size=size * 4, param_attr=mat_param_attr)
    return lstmemory(input=m, name=name, reverse=reverse, bias_attr=bias_param_attr,
                     param_attr=inner_param_attr, act=act, gate_act=gate_act,
                     state_act=state_act, layer_attr=lstm_cell_attr)


def lstmemory_group(input: LayerOutput, size: Optional[int] = None,
                    name: Optional[str] = None, reverse: bool = False,
                    param_attr=None, act=None, gate_act=None, state_act=None,
                    mixed_bias_attr=None, lstm_bias_attr=None,
                    mixed_layer_attr=None, lstm_layer_attr=None) -> LayerOutput:
    """LSTM built as an explicit recurrent_group (ref: networks.py
    lstmemory_group:726) — same math as lstmemory, but the step is visible so
    other layers can hook per-step values."""
    from paddle_tpu.dsl.layers import lstm_step_layer
    size = size or input.size // 4
    name = name or current_context().unique_name("lstm_group")

    def step(ipt):
        out_mem = memory(name=f"{name}_out", size=size)
        state_mem = memory(name=f"{name}_state", size=size)
        with mixed_layer(name=f"{name}_input_recurrent", size=size * 4,
                         act=LinearActivation(), bias_attr=mixed_bias_attr,
                         layer_attr=mixed_layer_attr) as m:
            m += full_matrix_projection(ipt, size=size * 4)
            m += full_matrix_projection(out_mem, size=size * 4, param_attr=param_attr)
        lstm = lstm_step_layer(
            input=m, state=state_mem, size=size, bias_attr=lstm_bias_attr,
            act=act, gate_act=gate_act, state_act=state_act, name=f"{name}_out",
            state_name=f"{name}_state", layer_attr=lstm_layer_attr)
        return lstm

    return recurrent_group(step=step, input=input, reverse=reverse,
                           name=f"{name}_recurrent_group")


def simple_gru(input: LayerOutput, size: int, name: Optional[str] = None,
               reverse: bool = False, mixed_param_attr=None, mixed_bias_attr=False,
               gru_param_attr=None, gru_bias_attr=None, act=None, gate_act=None,
               mixed_layer_attr=None, gru_layer_attr=None) -> LayerOutput:
    """fc(3*size) + grumemory (ref: networks.py simple_gru:937)."""
    with mixed_layer(name=f"{name}_transform" if name else None, size=size * 3,
                     act=LinearActivation(), bias_attr=mixed_bias_attr,
                     layer_attr=mixed_layer_attr) as m:
        m += full_matrix_projection(input, size=size * 3, param_attr=mixed_param_attr)
    return grumemory(input=m, name=name, reverse=reverse, bias_attr=gru_bias_attr,
                     param_attr=gru_param_attr, act=act, gate_act=gate_act,
                     layer_attr=gru_layer_attr)


def gru_group(input: LayerOutput, size: Optional[int] = None,
              name: Optional[str] = None, reverse: bool = False,
              gru_bias_attr=None, act=None, gate_act=None,
              gru_layer_attr=None) -> LayerOutput:
    """GRU as an explicit recurrent_group (ref: networks.py gru_group)."""
    size = size or input.size // 3
    name = name or current_context().unique_name("gru_group")

    def step(ipt):
        out_mem = memory(name=f"{name}_out", size=size)
        return gru_step_layer(input=ipt, output_mem=out_mem, size=size,
                              bias_attr=gru_bias_attr, act=act, gate_act=gate_act,
                              name=f"{name}_out", layer_attr=gru_layer_attr)

    return recurrent_group(step=step, input=input, reverse=reverse,
                           name=f"{name}_recurrent_group")


def bidirectional_lstm(input: LayerOutput, size: int, name: Optional[str] = None,
                       return_seq: bool = False, fwd_mat_param_attr=None,
                       bwd_mat_param_attr=None, **kwargs) -> LayerOutput:
    """(ref: networks.py bidirectional_lstm:1166)."""
    name = name or current_context().unique_name("bidirectional_lstm")
    fwd = simple_lstm(input=input, size=size, name=f"{name}_fwd", reverse=False,
                      mat_param_attr=fwd_mat_param_attr)
    bwd = simple_lstm(input=input, size=size, name=f"{name}_bwd", reverse=True,
                      mat_param_attr=bwd_mat_param_attr)
    if return_seq:
        return concat_layer(input=[fwd, bwd], name=name)
    fwd_end = last_seq(input=fwd, name=f"{name}_fwd_end")
    # reverse-scan outputs are position-aligned, so the backward summary
    # (full-sequence state) sits at position 0 (ref: networks.py:1156 first_seq)
    bwd_end = first_seq(input=bwd, name=f"{name}_bwd_end")
    return concat_layer(input=[fwd_end, bwd_end], name=name)


def simple_attention(encoded_sequence: LayerOutput,
                     encoded_proj: LayerOutput,
                     decoder_state: LayerOutput,
                     transform_param_attr=None,
                     softmax_param_attr=None,
                     name: Optional[str] = None,
                     fused: bool = True) -> LayerOutput:
    """Bahdanau-style additive attention (ref: networks.py simple_attention:1257).

    Must be called inside a recurrent_group step; encoded_sequence/encoded_proj
    are StaticInput aliases holding [B, T, D] sequences; decoder_state is a
    per-step [B, D] memory.  Returns the context vector [B, D].

    fused=True (default) emits ONE additive_attention_step layer — same
    math and the same two parameters (identical names, shapes and creation
    order, so seeded init and checkpoints match the composite) but executed
    as a single fused pass (pallas kernel on TPU; graph/layers_attn.py).
    fused=False builds the reference's 5-layer composite.
    """
    from paddle_tpu.config.schema import LayerConfig, LayerInput
    from paddle_tpu.dsl.layers import _make_param, addto_layer, scaling_layer
    from paddle_tpu.dsl.poolings import SumPooling
    name = name or current_context().unique_name("attention")
    if fused:
        w_name = _make_param(f"{name}_transform", 0,
                             [decoder_state.size, encoded_proj.size],
                             transform_param_attr)
        v_name = _make_param(f"{name}_scores", 0, [encoded_proj.size, 1],
                             softmax_param_attr)
        cfg = LayerConfig(name=name, type="additive_attention_step",
                          size=encoded_sequence.size)
        cfg.inputs.append(LayerInput(input_layer_name=decoder_state.name,
                                     input_parameter_name=w_name))
        cfg.inputs.append(LayerInput(input_layer_name=encoded_proj.name,
                                     input_parameter_name=v_name))
        cfg.inputs.append(LayerInput(input_layer_name=encoded_sequence.name))
        current_context().add_layer(cfg)
        return LayerOutput(name, "additive_attention_step",
                           encoded_sequence.size,
                           parents=[decoder_state, encoded_proj,
                                    encoded_sequence])
    with mixed_layer(name=f"{name}_transform", size=encoded_proj.size,
                     act=LinearActivation(), bias_attr=False) as proj_state:
        proj_state += full_matrix_projection(decoder_state, size=encoded_proj.size,
                                             param_attr=transform_param_attr)
    expanded = expand_layer(input=proj_state, expand_as=encoded_proj,
                            name=f"{name}_expand")
    combined = addto_layer(input=[expanded, encoded_proj], act=TanhActivation(),
                           name=f"{name}_combine")
    with mixed_layer(name=f"{name}_scores", size=1,
                     act=SequenceSoftmaxActivation(), bias_attr=False) as scores:
        scores += full_matrix_projection(combined, size=1,
                                         param_attr=softmax_param_attr)
    scaled = scaling_layer(weight=scores, input=encoded_sequence,
                           name=f"{name}_scale")
    return pooling_layer(input=scaled, pooling_type=SumPooling(),
                         name=f"{name}_pool")


def inputs(*layers) -> None:
    """Declare input order (ref: networks.py inputs:1376)."""
    ctx = current_context()
    ctx.model.input_layer_names = [l.name for l in layers]


def outputs(*layers) -> None:
    """Declare output layers (ref: networks.py outputs:1394)."""
    ctx = current_context()
    for l in layers:
        if l.name not in ctx.model.output_layer_names:
            ctx.model.output_layer_names.append(l.name)


def lstmemory_unit(input: LayerOutput, name: Optional[str] = None,
                   size: Optional[int] = None, param_attr=None, act=None,
                   gate_act=None, state_act=None, mixed_bias_attr=None,
                   lstm_bias_attr=None, mixed_layer_attr=None,
                   lstm_layer_attr=None,
                   get_output_layer_attr=None) -> LayerOutput:
    """One LSTM time step for use INSIDE a user recurrent_group (ref:
    networks.py lstmemory_unit:616) — not itself recurrent; typical use
    is attention decoders that need the per-step state visible.

    The reference contract: `input` is ALREADY projected to 4*size (the
    input-to-hidden matmuls are hoisted out of the unit for speed —
    ref networks.py:749-754), so it enters via identity_projection and
    only the recurrent out_mem projection holds parameters.  The cell
    state is published under `{name}_state` (the reference exposes it
    with a get_output_layer of that name; our lstm_step_layer publishes
    the state there directly, so get_output_layer_attr has nothing left
    to configure)."""
    if size is None:
        assert input.size % 4 == 0, (
            "lstmemory_unit expects its input pre-projected to 4*size "
            "(ref contract); add a mixed/fc projection before it")
        size = input.size // 4
    name = name or current_context().unique_name("lstmemory_unit")
    out_mem = memory(name=name, size=size)
    state_mem = memory(name=f"{name}_state", size=size)
    with mixed_layer(name=f"{name}_input_recurrent", size=size * 4,
                     act=LinearActivation(), bias_attr=mixed_bias_attr,
                     layer_attr=mixed_layer_attr) as m:
        m += identity_projection(input)
        m += full_matrix_projection(out_mem, size=size * 4,
                                    param_attr=param_attr)
    return lstm_step_layer(
        input=m, state=state_mem, size=size, bias_attr=lstm_bias_attr,
        act=act, gate_act=gate_act, state_act=state_act, name=name,
        state_name=f"{name}_state", layer_attr=lstm_layer_attr)


def gru_unit(input: LayerOutput, size: Optional[int] = None,
             name: Optional[str] = None, gru_bias_attr=None, act=None,
             gate_act=None, gru_layer_attr=None) -> LayerOutput:
    """One GRU time step for use INSIDE a user recurrent_group (ref:
    networks.py gru_unit:821)."""
    if size is None:
        assert input.size % 3 == 0
        size = input.size // 3
    name = name or current_context().unique_name("gru_unit")
    out_mem = memory(name=name, size=size)
    return gru_step_layer(input=input, output_mem=out_mem, size=size,
                          bias_attr=gru_bias_attr, act=act,
                          gate_act=gate_act, name=name,
                          layer_attr=gru_layer_attr)


def simple_gru2(input: LayerOutput, size: int, name: Optional[str] = None,
                reverse: bool = False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                mixed_layer_attr=None, gru_cell_attr=None) -> LayerOutput:
    """simple_gru via the fused grumemory cell (ref: networks.py
    simple_gru2:1019 — 'faster than simple_gru', which builds an explicit
    step group; here both compile to the same pallas/scan kernel)."""
    name = name or current_context().unique_name("simple_gru2")
    proj = fc_layer(input=input, size=size * 3, act=LinearActivation(),
                    bias_attr=mixed_bias_attr, param_attr=mixed_param_attr,
                    name=f"{name}_transform", layer_attr=mixed_layer_attr)
    return grumemory(input=proj, name=name, reverse=reverse,
                     bias_attr=gru_bias_attr, param_attr=gru_param_attr,
                     act=act, gate_act=gate_act, layer_attr=gru_cell_attr)


def bidirectional_gru(input: LayerOutput, size: int,
                      name: Optional[str] = None, return_seq: bool = False,
                      last_seq_attr=None, first_seq_attr=None,
                      concat_attr=None, concat_act=None,
                      **kwargs) -> LayerOutput:
    """Forward + backward simple_gru2, concatenated (ref: networks.py
    bidirectional_gru:1081): the full sequences when return_seq, else the
    two end-of-scan summaries (position-aligned reverse scan puts the
    backward summary at position 0).  Per-direction knobs use the
    reference's fwd_/bwd_ kwarg prefixes; anything else unknown errors
    rather than silently vanishing."""
    name = name or current_context().unique_name("bidirectional_gru")
    fwd_kw = {k[len("fwd_"):]: v for k, v in kwargs.items()
              if k.startswith("fwd_")}
    bwd_kw = {k[len("bwd_"):]: v for k, v in kwargs.items()
              if k.startswith("bwd_")}
    unknown = [k for k in kwargs
               if not (k.startswith("fwd_") or k.startswith("bwd_"))]
    if unknown:
        raise TypeError(
            f"bidirectional_gru got unexpected kwargs {unknown}; "
            f"per-direction options take fwd_/bwd_ prefixes")
    fwd = simple_gru2(input=input, size=size, name=f"{name}_fwd",
                      reverse=False, **fwd_kw)
    bwd = simple_gru2(input=input, size=size, name=f"{name}_bwd",
                      reverse=True, **bwd_kw)
    if return_seq:
        return concat_layer(input=[fwd, bwd], name=name, act=concat_act,
                            layer_attr=concat_attr)
    fwd_end = last_seq(input=fwd, name=f"{name}_fwd_end",
                       layer_attr=last_seq_attr)
    bwd_end = first_seq(input=bwd, name=f"{name}_bwd_end",
                        layer_attr=first_seq_attr)
    return concat_layer(input=[fwd_end, bwd_end], name=name, act=concat_act,
                        layer_attr=concat_attr)


def img_conv_bn_pool(input: LayerOutput, filter_size: int, num_filters: int,
                     pool_size: int, name: Optional[str] = None,
                     pool_type=None, act=None, groups: int = 1,
                     conv_stride: int = 1, conv_padding: int = 0,
                     conv_bias_attr=None, num_channel: Optional[int] = None,
                     conv_param_attr=None, shared_bias: bool = True,
                     conv_layer_attr=None, bn_param_attr=None,
                     bn_bias_attr=None, bn_layer_attr=None,
                     pool_stride: int = 1, pool_padding: int = 0,
                     pool_layer_attr=None) -> LayerOutput:
    """conv -> batch_norm -> pool composite (ref: networks.py
    img_conv_bn_pool:232) — the linear-activation conv feeds BN, which
    carries the nonlinearity."""
    name = name or current_context().unique_name("img_conv_bn_pool")
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, act=LinearActivation(), groups=groups,
        stride=conv_stride, padding=conv_padding, bias_attr=conv_bias_attr,
        param_attr=conv_param_attr, shared_biases=shared_bias,
        name=f"{name}_conv", layer_attr=conv_layer_attr)
    bn = batch_norm_layer(input=conv, act=act, name=f"{name}_bn",
                          bias_attr=bn_bias_attr, param_attr=bn_param_attr,
                          layer_attr=bn_layer_attr)
    return img_pool_layer(input=bn, pool_size=pool_size, name=f"{name}_pool",
                          pool_type=pool_type, stride=pool_stride,
                          padding=pool_padding, layer_attr=pool_layer_attr)


# ref: networks.py:137 — text_conv_pool IS sequence_conv_pool by another name
text_conv_pool = sequence_conv_pool
