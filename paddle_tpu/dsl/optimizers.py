"""Optimizer DSL: settings() + optimizer descriptors
(ref: trainer_config_helpers/optimizers.py: settings:358, Momentum/Adam/...)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.dsl.base import current_context

__all__ = [
    "settings", "MomentumOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "AdaGradOptimizer", "DecayedAdaGradOptimizer", "AdaDeltaOptimizer",
    "RMSPropOptimizer", "L2Regularization", "L1Regularization",
    "GradientClippingThreshold", "ModelAverage",
    "Optimizer", "BaseRegularization", "BaseSGDOptimizer",
]


class Optimizer:
    """Root of the settings-applying hierarchy (ref: optimizers.py
    Optimizer:28) — exists so user isinstance checks from reference-era
    configs keep working."""

    def apply(self, opt) -> None:   # pragma: no cover - abstract
        raise NotImplementedError


class BaseSGDOptimizer(Optimizer):
    learning_method = "momentum"

    def apply(self, opt) -> None:
        opt.learning_method = self.learning_method


class MomentumOptimizer(BaseSGDOptimizer):
    learning_method = "momentum"

    def __init__(self, momentum: float = 0.0, sparse: bool = False):
        self.momentum = momentum
        self.sparse = sparse

    def apply(self, opt) -> None:
        opt.learning_method = "sparse_momentum" if self.sparse else "momentum"
        opt.momentum = self.momentum


class AdamOptimizer(BaseSGDOptimizer):
    learning_method = "adam"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def apply(self, opt) -> None:
        opt.learning_method = "adam"
        opt.adam_beta1 = self.beta1
        opt.adam_beta2 = self.beta2
        opt.adam_epsilon = self.epsilon


class AdamaxOptimizer(BaseSGDOptimizer):
    learning_method = "adamax"

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999):
        self.beta1, self.beta2 = beta1, beta2

    def apply(self, opt) -> None:
        opt.learning_method = "adamax"
        opt.adam_beta1 = self.beta1
        opt.adam_beta2 = self.beta2


class AdaGradOptimizer(BaseSGDOptimizer):
    learning_method = "adagrad"

    def __init__(self, epsilon: float = 1e-6):
        self.epsilon = epsilon

    def apply(self, opt) -> None:
        opt.learning_method = "adagrad"
        opt.ada_epsilon = self.epsilon


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    learning_method = "decayed_adagrad"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def apply(self, opt) -> None:
        opt.learning_method = "decayed_adagrad"
        opt.ada_rho = self.rho
        opt.ada_epsilon = self.epsilon


class AdaDeltaOptimizer(BaseSGDOptimizer):
    learning_method = "adadelta"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def apply(self, opt) -> None:
        opt.learning_method = "adadelta"
        opt.ada_rho = self.rho
        opt.ada_epsilon = self.epsilon


class RMSPropOptimizer(BaseSGDOptimizer):
    learning_method = "rmsprop"

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def apply(self, opt) -> None:
        opt.learning_method = "rmsprop"
        opt.ada_rho = self.rho
        opt.ada_epsilon = self.epsilon


class BaseRegularization(Optimizer):
    """(ref: optimizers.py BaseRegularization:294)."""


class L2Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate

    def apply(self, opt) -> None:
        opt.l2_weight = self.rate


class L1Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate

    def apply(self, opt) -> None:
        opt.l1_weight = self.rate


class GradientClippingThreshold(Optimizer):
    def __init__(self, threshold: float):
        self.threshold = threshold

    def apply(self, opt) -> None:
        opt.gradient_clipping_threshold = self.threshold


class ModelAverage(Optimizer):
    def __init__(self, average_window: float, max_average_window: Optional[int] = None,
                 do_average_in_cpu: bool = False):
        self.average_window = average_window
        self.max_average_window = max_average_window
        self.do_average_in_cpu = do_average_in_cpu

    def apply(self, opt) -> None:
        opt.average_window = self.average_window
        if self.max_average_window:
            opt.max_average_window = self.max_average_window
        opt.do_average_in_cpu = self.do_average_in_cpu


def settings(
    batch_size: int,
    learning_rate: float = 1e-3,
    learning_method=None,
    regularization=None,
    learning_rate_decay_a: float = 0.0,
    learning_rate_decay_b: float = 0.0,
    learning_rate_schedule: str = "poly",
    learning_rate_args: str = "",
    model_average=None,
    gradient_clipping_threshold=None,
    dtype: str = "float32",
    compute_dtype: str = "",
    **kwargs,
) -> None:
    """Set global optimization settings (ref: optimizers.py settings:358)."""
    opt = current_context().opt
    opt.batch_size = batch_size
    opt.learning_rate = learning_rate
    opt.learning_rate_decay_a = learning_rate_decay_a
    opt.learning_rate_decay_b = learning_rate_decay_b
    opt.learning_rate_schedule = learning_rate_schedule
    opt.learning_rate_args = learning_rate_args
    opt.dtype = dtype
    opt.compute_dtype = compute_dtype
    if learning_method is not None:
        learning_method.apply(opt)
    regs = regularization if isinstance(regularization, (list, tuple)) else (
        [regularization] if regularization is not None else [])
    for r in regs:
        r.apply(opt)
    if model_average is not None:
        model_average.apply(opt)
    if gradient_clipping_threshold is not None:
        if isinstance(gradient_clipping_threshold, GradientClippingThreshold):
            gradient_clipping_threshold.apply(opt)
        else:
            opt.gradient_clipping_threshold = float(gradient_clipping_threshold)
    for k, v in kwargs.items():
        if hasattr(opt, k):
            setattr(opt, k, v)
