"""Data source declaration (ref: trainer_config_helpers/data_sources.py
define_py_data_sources2:173)."""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.config.schema import DataConfig
from paddle_tpu.dsl.base import current_context

__all__ = ["define_py_data_sources2", "define_multi_py_data_sources2",
           "define_ptsh_data_sources"]


def define_py_data_sources2(
    train_list: Optional[str],
    test_list: Optional[str],
    module: str,
    obj: str,
    args: Any = None,
    constant_slots: Optional[list] = None,
) -> None:
    """Declare train/test providers backed by @provider functions
    (ref: data_sources.py:173; PyDataProvider2).  `constant_slots` appends
    fixed-value [B, 1] slots after the provider's slots (ref:
    config_parser.py:888; DataProvider.cpp:177-195)."""
    ctx = current_context()
    import json
    args_str = json.dumps(args) if args is not None else ""
    const = [float(v) for v in (constant_slots or [])]
    if train_list is not None:
        ctx.data = DataConfig(type="py2", files=train_list, load_data_module=module,
                              load_data_object=obj, load_data_args=args_str,
                              constant_slots=const)
    if test_list is not None:
        ctx.test_data = DataConfig(type="py2", files=test_list, load_data_module=module,
                                   load_data_object=obj, load_data_args=args_str,
                                   constant_slots=const)


def define_multi_py_data_sources2(
    train_sources: Optional[list] = None,
    test_sources: Optional[list] = None,
    ratios: Optional[list] = None,
) -> None:
    """Declare a multi-source provider that mixes several @provider streams
    by data ratio into one training stream (ref:
    gserver/dataproviders/MultiDataProvider.{h,cpp}).

    Each source is a dict: {"files": ..., "module": ..., "obj": ...,
    "args": optional}; all sources must share one slot schema.  `ratios`
    weights how many samples each source contributes per mixing round
    (default: equal).  Test sources are concatenated, not mixed.
    """
    import json as _json

    ctx = current_context()

    def _sub(src) -> DataConfig:
        return DataConfig(
            type="py2", files=src["files"], load_data_module=src["module"],
            load_data_object=src["obj"],
            load_data_args=(_json.dumps(src["args"]) if src.get("args")
                            is not None else ""))

    if train_sources:
        ctx.data = DataConfig(type="multi",
                              sub_configs=[_sub(s) for s in train_sources],
                              data_ratios=list(ratios or []))
    if test_sources:
        ctx.test_data = DataConfig(type="multi",
                                   sub_configs=[_sub(s) for s in test_sources])


def define_ptsh_data_sources(
    train: Optional[str],
    test: Optional[str] = None,
    names: Optional[list] = None,
) -> None:
    """Declare train/test sources backed by PTSH binary shards read by the
    native C++ loader (paddle_tpu/io/).  `train`/`test` are a shard dir,
    glob, or file-list; `names` maps shard slots to data-layer names (defaults
    to the model's data layers in declaration order)."""
    ctx = current_context()
    import json
    args_str = json.dumps({"names": names}) if names else ""
    if train is not None:
        ctx.data = DataConfig(type="ptsh", files=train, load_data_args=args_str)
    if test is not None:
        ctx.test_data = DataConfig(type="ptsh", files=test, load_data_args=args_str)
