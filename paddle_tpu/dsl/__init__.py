"""User-facing configuration DSL.

The TPU framework's equivalent of the reference's
`trainer_config_helpers` package (ref: python/paddle/trainer_config_helpers/):
layer constructors that assemble a ModelConfig, optimizer `settings()`,
activation/pooling/attr descriptor classes, and composite networks.
"""

from paddle_tpu.dsl.activations import *  # noqa: F401,F403
from paddle_tpu.dsl.attrs import (  # noqa: F401
    ExtraAttr, ExtraLayerAttribute, ParamAttr, ParameterAttribute,
)
from paddle_tpu.dsl.poolings import *  # noqa: F401,F403
from paddle_tpu.dsl.layers import *  # noqa: F401,F403
from paddle_tpu.dsl.optimizers import *  # noqa: F401,F403
from paddle_tpu.dsl.networks import *  # noqa: F401,F403
from paddle_tpu.dsl.evaluators import *  # noqa: F401,F403
from paddle_tpu.dsl.default_decorators import (  # noqa: F401
    wrap_act_default, wrap_bias_attr_default, wrap_name_default,
    wrap_param_attr_default, wrap_param_default,
)
from paddle_tpu.dsl.data_sources import (  # noqa: F401
    define_multi_py_data_sources2, define_ptsh_data_sources,
    define_py_data_sources2,
)
# legacy recurrent building blocks: use as
# `from paddle_tpu.dsl import recurrent_units` (the reference's
# `import trainer.recurrent_units` form)
from paddle_tpu.dsl import recurrent_units  # noqa: F401
