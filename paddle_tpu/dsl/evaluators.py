"""Evaluator DSL (ref: trainer_config_helpers/evaluators.py)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.config.schema import EvaluatorConfig
from paddle_tpu.dsl.base import LayerOutput, current_context

__all__ = [
    "classification_error_evaluator", "auc_evaluator", "sum_evaluator",
    "column_sum_evaluator", "precision_recall_evaluator", "pnpair_evaluator",
    "chunk_evaluator", "ctc_error_evaluator", "value_printer_evaluator",
    "rank_auc_evaluator", "seq_classification_error_evaluator",
    "maxid_printer_evaluator", "seqtext_printer_evaluator",
    "classification_error_printer_evaluator", "gradient_printer_evaluator",
    "maxframe_printer_evaluator", "evaluator_base",
]


def _add(type_: str, inputs: list[LayerOutput], name: Optional[str], **extra) -> EvaluatorConfig:
    ctx = current_context()
    cfg = EvaluatorConfig(
        name=name or ctx.unique_name(type_), type=type_,
        input_layer_names=[i.name for i in inputs])
    for k, v in extra.items():
        if v is not None:
            setattr(cfg, k, v)
    return ctx.add_evaluator(cfg)


def classification_error_evaluator(input: LayerOutput, label: LayerOutput,
                                   name=None, weight=None,
                                   threshold: Optional[float] = None) -> None:
    """(ref: Evaluator.cpp ClassificationErrorEvaluator)."""
    ins = [input, label] + ([weight] if weight else [])
    _add("classification_error", ins, name,
         classification_threshold=threshold)


def auc_evaluator(input: LayerOutput, label: LayerOutput, name=None,
                  weight=None) -> None:
    """(ref: Evaluator.cpp AucEvaluator)."""
    ins = [input, label] + ([weight] if weight else [])
    _add("auc", ins, name)


def sum_evaluator(input: LayerOutput, name=None, weight=None) -> None:
    ins = [input] + ([weight] if weight else [])
    _add("sum", ins, name)


def column_sum_evaluator(input: LayerOutput, name=None, weight=None) -> None:
    ins = [input] + ([weight] if weight else [])
    _add("column_sum", ins, name)


def precision_recall_evaluator(input: LayerOutput, label: LayerOutput, name=None,
                               positive_label: int = -1, weight=None) -> None:
    """(ref: PrecisionRecallEvaluator)."""
    ins = [input, label] + ([weight] if weight else [])
    _add("precision_recall", ins, name, positive_label=positive_label)


def pnpair_evaluator(input: LayerOutput, label: LayerOutput, info: LayerOutput,
                     name=None, weight=None) -> None:
    """(ref: PnpairEvaluator)."""
    ins = [input, label, info] + ([weight] if weight else [])
    _add("pnpair", ins, name)


def chunk_evaluator(input: LayerOutput, label: LayerOutput, chunk_scheme: str,
                    num_chunk_types: int, name=None,
                    excluded_chunk_types: Optional[list] = None) -> None:
    """NER-style chunk F1 (ref: ChunkEvaluator.cpp)."""
    _add("chunk", [input, label], name, chunk_scheme=chunk_scheme,
         num_chunk_types=num_chunk_types,
         excluded_chunk_types=excluded_chunk_types or [])


def ctc_error_evaluator(input: LayerOutput, label: LayerOutput, name=None) -> None:
    """Edit-distance over CTC decodes (ref: CTCErrorEvaluator.cpp)."""
    _add("ctc_edit_distance", [input, label], name)


def value_printer_evaluator(input: LayerOutput, name=None) -> None:
    _add("value_printer", [input], name)


def rank_auc_evaluator(input: LayerOutput, label: LayerOutput, name=None,
                       weight=None) -> None:
    """Per-query ranking AUC over sequences (ref: RankAucEvaluator)."""
    ins = [input, label] + ([weight] if weight else [])
    _add("rankauc", ins, name)


def seq_classification_error_evaluator(input: LayerOutput, label: LayerOutput,
                                       name=None,
                                       threshold: Optional[float] = None) -> None:
    """Sequence-level error: wrong if any frame is wrong
    (ref: SequenceClassificationErrorEvaluator)."""
    _add("seq_classification_error", [input, label], name,
         classification_threshold=threshold)


def maxid_printer_evaluator(input: LayerOutput, name=None) -> None:
    _add("max_id_printer", [input], name)


def seqtext_printer_evaluator(input: LayerOutput, name=None,
                              result_file: str = "", dict_file: str = "",
                              delimited: bool = True) -> None:
    """Print/write decoded id sequences (ref: SequenceTextPrinter —
    result_file/dict_file/delimited)."""
    _add("seq_text_printer", [input], name, result_file=result_file,
         dict_file=dict_file, delimited=delimited)


def classification_error_printer_evaluator(input: LayerOutput,
                                           label: LayerOutput,
                                           name=None) -> None:
    _add("classification_error_printer", [input, label], name)


def gradient_printer_evaluator(input: LayerOutput, name=None) -> None:
    """Print the layer's OUTPUT GRADIENT each batch (ref: Evaluator.cpp
    GradientPrinter).  The trainer recreates the grad buffer autodiff
    elides via an additive-zero probe at the layer."""
    _add("gradient_printer", [input], name)


def maxframe_printer_evaluator(input: LayerOutput, name=None) -> None:
    """Print each sequence's value-maximizing frame (ref: Evaluator.cpp
    MaxFramePrinter)."""
    _add("max_frame_printer", [input], name)


def evaluator_base(input, type: str, label: Optional[LayerOutput] = None,
                   weight: Optional[LayerOutput] = None,
                   name: Optional[str] = None, **extra) -> None:
    """Generic evaluator constructor (ref: evaluators.py evaluator_base:60)
    — the escape hatch for evaluator types without a dedicated helper:
    assembles [input, label, weight] in the reference's argument order and
    passes every remaining kwarg onto the EvaluatorConfig."""
    inputs = [input] if isinstance(input, LayerOutput) else list(input)
    if label is not None:
        inputs.append(label)
    if weight is not None:
        inputs.append(weight)
    _add(type, inputs, name, **extra)
