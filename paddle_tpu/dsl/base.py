"""DSL assembly context and LayerOutput value objects.

Plays the role of the reference's config_parser global state
(ref: python/paddle/trainer/config_parser.py: g_config / g_layer_map /
g_parameter_map and the @config_layer classes' size inference) — but as an
explicit context object, no exec-global mutation required.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Any, Optional

from paddle_tpu.config.schema import (
    DataConfig,
    EvaluatorConfig,
    LayerConfig,
    ModelConfig,
    OptimizationConfig,
    ParameterConfig,
    SubModelConfig,
    TrainerConfig,
)


class ConfigContext:
    """Collects layers/parameters/evaluators while a config runs."""

    def __init__(self) -> None:
        self.model = ModelConfig()
        self.opt = OptimizationConfig()
        self.data: Optional[DataConfig] = None
        self.test_data: Optional[DataConfig] = None
        self._names: set[str] = set()
        self._param_names: set[str] = set()
        self._counters: dict[str, int] = {}
        # recurrent-group nesting state
        self.group_stack: list[SubModelConfig] = []
        self.input_types: dict[str, Any] = {}

    # -- naming -----------------------------------------------------------
    def unique_name(self, prefix: str) -> str:
        n = self._counters.get(prefix, 0)
        self._counters[prefix] = n + 1
        name = f"__{prefix}_{n}__"
        while name in self._names:
            n += 1
            self._counters[prefix] = n + 1
            name = f"__{prefix}_{n}__"
        return name

    # -- registration -----------------------------------------------------
    def add_layer(self, cfg: LayerConfig) -> LayerConfig:
        if cfg.name in self._names:
            raise ValueError(f"duplicate layer name {cfg.name!r}")
        self._names.add(cfg.name)
        self.model.layers.append(cfg)
        if self.group_stack:
            self.group_stack[-1].layer_names.append(cfg.name)
        return cfg

    def add_parameter(self, cfg: ParameterConfig) -> ParameterConfig:
        if cfg.name in self._param_names:
            raise ValueError(f"duplicate parameter name {cfg.name!r}")
        self._param_names.add(cfg.name)
        self.model.parameters.append(cfg)
        return cfg

    def has_parameter(self, name: str) -> bool:
        return name in self._param_names

    def add_evaluator(self, cfg: EvaluatorConfig) -> EvaluatorConfig:
        self.model.evaluators.append(cfg)
        return cfg

    def to_trainer_config(self) -> TrainerConfig:
        return TrainerConfig(
            model_config=self.model, opt_config=self.opt,
            data_config=self.data, test_data_config=self.test_data)


_current: list[ConfigContext] = []


def current_context() -> ConfigContext:
    if not _current:
        _current.append(ConfigContext())  # implicit context for ad-hoc use
    return _current[-1]


@contextlib.contextmanager
def config_context():
    ctx = ConfigContext()
    _current.append(ctx)
    try:
        yield ctx
    finally:
        _current.pop()


def reset_context() -> ConfigContext:
    """Drop any implicit context and start fresh (used by parse_config)."""
    _current.clear()
    ctx = ConfigContext()
    _current.append(ctx)
    return ctx


@dataclass
class LayerOutput:
    """Handle returned by every layer constructor
    (ref: trainer_config_helpers/layers.py LayerOutput)."""

    name: str
    layer_type: str
    size: int = 0
    parents: list["LayerOutput"] = field(default_factory=list)
    activation: Any = None
    # image geometry riding along for conv size inference
    num_filters: int = 0
    img_size: int = 0
    img_size_y: int = 0
    # sequence nesting level: 0 = sample, 1 = sequence, 2 = nested sequence
    seq_level: int = 0

    def __repr__(self) -> str:
        return f"LayerOutput({self.name!r}, {self.layer_type!r}, size={self.size})"
