"""Pre-DSL recurrent building blocks — LSTM/GRU units and layer groups with
explicit parameter-name sharing (ref: python/paddle/trainer/
recurrent_units.py:32-354).

The reference's units are raw config_parser calls (Layer/Memory/Projection);
here they are thin compositions over the modern DSL with the same public
surface and the same parameter-naming contract: two units created with one
`para_prefix` share `<prefix>_input_recurrent.w/.b` (+ `<prefix>_check.b`
for LSTM peepholes / `<prefix>_gate_recurrent.w` for GRU), which is how the
reference expresses weight tying across recurrent unit instances.

The reference's *Naive variants build the identical math from explicit
per-gate expression layers (kept there for debugging its fused C++ step
layers); under XLA both forms compile to the same fused program, so the
Naive names alias the fused implementations.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.dsl.activations import LinearActivation
from paddle_tpu.dsl.attrs import ExtraLayerAttribute, ParameterAttribute
from paddle_tpu.dsl.base import current_context
from paddle_tpu.dsl.layers import (LayerOutput, _Projection,
                                   full_matrix_projection, gru_step_layer,
                                   identity_projection, lstm_step_layer,
                                   memory, mixed_layer, recurrent_group)

__all__ = [
    "LstmRecurrentUnit", "LstmRecurrentUnitNaive", "LstmRecurrentLayerGroup",
    "GatedRecurrentUnit", "GatedRecurrentUnitNaive",
    "GatedRecurrentLayerGroup",
]


def _act(name):
    """Reference configs pass activation TYPE STRINGS here; the step layers
    accept the string directly.  Unknown names fail loudly instead of
    silently substituting a default."""
    if not isinstance(name, str):
        return name
    if not name:
        return "linear"
    from paddle_tpu.ops.activations import activation_registry
    if name not in activation_registry:
        raise ValueError(f"unknown activation type {name!r}")
    return name


def _as_projection(p, width: int) -> _Projection:
    if isinstance(p, _Projection):
        return p
    assert isinstance(p, LayerOutput), f"bad unit input: {type(p)}"
    return full_matrix_projection(p, size=width)


def LstmRecurrentUnit(name: str, size: int, active_type: str = "tanh",
                      state_active_type: str = "tanh",
                      gate_active_type: str = "sigmoid",
                      inputs=(), para_prefix: Optional[str] = None,
                      error_clipping_threshold: float = 0,
                      out_memory: Optional[LayerOutput] = None) -> LayerOutput:
    """One LSTM unit inside a recurrent_group step (ref:
    recurrent_units.py:32-72): mixed(4*size) over `inputs` + the recurrent
    projection of the output memory, then a fused lstm_step."""
    para_prefix = para_prefix or name
    if out_memory is None:
        out_memory = memory(name=name, size=size)
    state_memory = memory(name=f"{name}_state", size=size)

    extra = (ExtraLayerAttribute(error_clipping_threshold=error_clipping_threshold)
             if error_clipping_threshold else None)
    with mixed_layer(
            name=f"{name}_input_recurrent", size=size * 4,
            act=LinearActivation(),
            bias_attr=ParameterAttribute(
                name=f"{para_prefix}_input_recurrent.b", initial_std=0),
            layer_attr=extra) as m:
        for p in inputs:
            m += _as_projection(p, size * 4)
        m += full_matrix_projection(
            out_memory, size=size * 4,
            param_attr=ParameterAttribute(
                name=f"{para_prefix}_input_recurrent.w"))
    return lstm_step_layer(
        input=m, state=state_memory, size=size, name=name,
        state_name=f"{name}_state",
        bias_attr=ParameterAttribute(name=f"{para_prefix}_check.b"),
        act=_act(active_type), gate_act=_act(gate_active_type),
        state_act=_act(state_active_type))


# identical math; the reference's Naive form exists to cross-check its fused
# C++ kernels — XLA fuses both identically
LstmRecurrentUnitNaive = LstmRecurrentUnit


def LstmRecurrentLayerGroup(name: str, size: int, active_type: str = "tanh",
                            state_active_type: str = "tanh",
                            gate_active_type: str = "sigmoid",
                            inputs=(), para_prefix: Optional[str] = None,
                            error_clipping_threshold: float = 0,
                            seq_reversed: bool = False) -> LayerOutput:
    """LSTM over a sequence built from the unit (ref:
    recurrent_units.py:156-191): the input projections apply OUTSIDE the
    group in one mixed(4*size); each step consumes its slice by identity."""
    with mixed_layer(name=f"{name}_transform_input", size=size * 4,
                     act=LinearActivation(), bias_attr=False) as transform:
        for p in inputs:
            transform += _as_projection(p, size * 4)

    def step(ipt):
        return LstmRecurrentUnit(
            name=name, size=size, active_type=active_type,
            state_active_type=state_active_type,
            gate_active_type=gate_active_type,
            inputs=[identity_projection(ipt)], para_prefix=para_prefix,
            error_clipping_threshold=error_clipping_threshold)

    return recurrent_group(step=step, input=transform, reverse=seq_reversed,
                           name=f"{name}_layer_group")


def GatedRecurrentUnit(name: str, size: int, active_type: str = "tanh",
                       gate_active_type: str = "sigmoid",
                       inputs=(), para_prefix: Optional[str] = None,
                       error_clipping_threshold: float = 0,
                       out_memory: Optional[LayerOutput] = None) -> LayerOutput:
    """One GRU unit inside a recurrent_group step (ref:
    recurrent_units.py:202-236)."""
    para_prefix = para_prefix or name
    if out_memory is None:
        out_memory = memory(name=name, size=size)

    extra = (ExtraLayerAttribute(error_clipping_threshold=error_clipping_threshold)
             if error_clipping_threshold else None)
    with mixed_layer(
            name=f"{name}_input_proj", size=size * 3,
            act=LinearActivation(),
            bias_attr=ParameterAttribute(
                name=f"{para_prefix}_input_proj.b", initial_std=0),
            layer_attr=extra) as m:
        for p in inputs:
            m += _as_projection(p, size * 3)
    return gru_step_layer(
        input=m, output_mem=out_memory, size=size, name=name,
        param_attr=ParameterAttribute(name=f"{para_prefix}_gate_recurrent.w"),
        bias_attr=ParameterAttribute(name=f"{para_prefix}_gate_recurrent.b"),
        act=_act(active_type), gate_act=_act(gate_active_type))


GatedRecurrentUnitNaive = GatedRecurrentUnit


def GatedRecurrentLayerGroup(name: str, size: int, active_type: str = "tanh",
                             gate_active_type: str = "sigmoid",
                             inputs=(), para_prefix: Optional[str] = None,
                             error_clipping_threshold: float = 0,
                             seq_reversed: bool = False) -> LayerOutput:
    """GRU over a sequence built from the unit (ref:
    recurrent_units.py:321-354)."""
    with mixed_layer(name=f"{name}_transform_input", size=size * 3,
                     act=LinearActivation(), bias_attr=False) as transform:
        for p in inputs:
            transform += _as_projection(p, size * 3)

    def step(ipt):
        return GatedRecurrentUnit(
            name=name, size=size, active_type=active_type,
            gate_active_type=gate_active_type,
            inputs=[identity_projection(ipt)], para_prefix=para_prefix,
            error_clipping_threshold=error_clipping_threshold)

    return recurrent_group(step=step, input=transform, reverse=seq_reversed,
                           name=f"{name}_layer_group")
