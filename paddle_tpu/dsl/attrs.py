"""Parameter / layer attribute descriptors
(ref: trainer_config_helpers/attrs.py ParameterAttribute:58, ExtraLayerAttribute)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.config.schema import ParameterConfig

__all__ = ["ParameterAttribute", "ExtraLayerAttribute", "ParamAttr", "ExtraAttr"]


class ParameterAttribute:
    """User-specified parameter settings, merged into ParameterConfig."""

    def __init__(
        self,
        name: Optional[str] = None,
        is_static: bool = False,
        initial_std: Optional[float] = None,
        initial_mean: Optional[float] = None,
        initial_max: Optional[float] = None,
        initial_min: Optional[float] = None,
        l1_rate: Optional[float] = None,
        l2_rate: Optional[float] = None,
        learning_rate: Optional[float] = None,
        momentum: Optional[float] = None,
        sparse_update: bool = False,
        gradient_clipping_threshold: Optional[float] = None,
        partition_spec: Optional[list] = None,
        update_hooks: Optional[list] = None,
    ):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.sparse_update = sparse_update
        self.gradient_clipping_threshold = gradient_clipping_threshold
        self.partition_spec = partition_spec
        self.update_hooks = update_hooks

    def apply(self, cfg: ParameterConfig) -> ParameterConfig:
        if self.name:
            cfg.name = self.name
        cfg.is_static = self.is_static
        if self.initial_min is not None or self.initial_max is not None:
            lo = self.initial_min if self.initial_min is not None else 0.0
            hi = self.initial_max if self.initial_max is not None else 1.0
            cfg.initial_strategy = "uniform"
            cfg.initial_mean = (lo + hi) / 2.0
            cfg.initial_std = (hi - lo) / 2.0
            cfg.initial_smart = False
        if self.initial_std is not None:
            cfg.initial_std = self.initial_std
            cfg.initial_smart = False
        if self.initial_mean is not None:
            cfg.initial_mean = self.initial_mean
        if self.l1_rate is not None:
            cfg.decay_rate_l1 = self.l1_rate
        if self.l2_rate is not None:
            cfg.decay_rate = self.l2_rate
        if self.learning_rate is not None:
            cfg.learning_rate = self.learning_rate
        if self.momentum is not None:
            cfg.momentum = self.momentum
        if self.sparse_update:
            cfg.sparse_update = True
        if self.gradient_clipping_threshold is not None:
            cfg.gradient_clipping_threshold = self.gradient_clipping_threshold
        if self.partition_spec is not None:
            cfg.partition_spec = list(self.partition_spec)
        if self.update_hooks is not None:
            cfg.update_hooks = [dict(h) for h in self.update_hooks]
        return cfg


class ExtraLayerAttribute:
    """Extra layer settings: dropout etc. (ref: attrs.py ExtraLayerAttribute)."""

    def __init__(self, error_clipping_threshold: Optional[float] = None,
                 drop_rate: Optional[float] = None, device: Optional[int] = None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ParamAttr = ParameterAttribute
ExtraAttr = ExtraLayerAttribute
