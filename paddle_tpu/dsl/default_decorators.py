"""Default-filling decorators for user-defined DSL extensions (ref:
python/paddle/trainer_config_helpers/default_decorators.py:30-131).

User configs in the wild decorate their own composite-layer helpers with
these to inherit the framework's defaulting behavior: a missing/None
kwarg is filled from a factory before the call.  The TPU rewrite keeps
the public API; name generation routes through the config context's
unique_name so decorator-produced names can never collide with layer
auto-names.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence


def wrap_param_default(param_names: Sequence[str],
                       default_factory: Callable,
                       not_set_callback=None):
    """Fill each named kwarg with default_factory(func) when unset/None."""
    assert param_names and all(isinstance(n, str) for n in param_names)
    if not_set_callback is None:
        def not_set_callback(kwargs, name):
            return name not in kwargs or kwargs[name] is None

    def deco(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            for name in param_names:
                if not_set_callback(kwargs, name):
                    kwargs[name] = default_factory(func)
            return func(*args, **kwargs)

        return wrapper

    return deco


def wrap_name_default(name_prefix: Optional[str] = None):
    """Fill `name=None` with a unique generated name (prefix defaults to
    the wrapped function's own name)."""
    def factory(func):
        from paddle_tpu.dsl.base import current_context
        return current_context().unique_name(name_prefix or func.__name__)

    return wrap_param_default(["name"], factory)


def wrap_param_attr_default(param_names: Optional[Sequence[str]] = None,
                            default_factory: Optional[Callable] = None):
    from paddle_tpu.dsl.attrs import ParameterAttribute
    factory = default_factory or (lambda func: ParameterAttribute())
    return wrap_param_default(list(param_names or ["param_attr"]), factory)


def wrap_bias_attr_default(param_names: Optional[Sequence[str]] = None,
                           default_factory: Optional[Callable] = None,
                           has_bias: bool = True):
    from paddle_tpu.dsl.attrs import ParameterAttribute

    def factory(func):
        if default_factory is not None:
            return default_factory(func)
        return ParameterAttribute() if has_bias else False

    return wrap_param_default(list(param_names or ["bias_attr"]), factory)


def wrap_act_default(param_names: Optional[Sequence[str]] = None,
                     act=None):
    if act is None:
        from paddle_tpu.dsl.activations import TanhActivation
        act = TanhActivation()
    return wrap_param_default(list(param_names or ["act"]), lambda f: act)
