"""Activation descriptors (ref: trainer_config_helpers/activations.py)."""

from __future__ import annotations

__all__ = [
    "BaseActivation", "LinearActivation", "IdentityActivation", "TanhActivation",
    "SigmoidActivation", "SoftmaxActivation", "SequenceSoftmaxActivation",
    "ReluActivation", "BReluActivation", "SoftReluActivation", "STanhActivation",
    "AbsActivation", "SquareActivation", "ExpActivation", "LogActivation",
    "GeluActivation",
]


class BaseActivation:
    name: str = ""

    def __repr__(self) -> str:
        return type(self).__name__


class LinearActivation(BaseActivation):
    name = ""


IdentityActivation = LinearActivation


class TanhActivation(BaseActivation):
    name = "tanh"


class SigmoidActivation(BaseActivation):
    name = "sigmoid"


class SoftmaxActivation(BaseActivation):
    name = "softmax"


class SequenceSoftmaxActivation(BaseActivation):
    name = "sequence_softmax"


class ReluActivation(BaseActivation):
    name = "relu"


class BReluActivation(BaseActivation):
    name = "brelu"


class SoftReluActivation(BaseActivation):
    name = "softrelu"


class STanhActivation(BaseActivation):
    name = "stanh"


class AbsActivation(BaseActivation):
    name = "abs"


class SquareActivation(BaseActivation):
    name = "square"


class ExpActivation(BaseActivation):
    name = "exponential"


class LogActivation(BaseActivation):
    name = "log"


class GeluActivation(BaseActivation):
    """tanh-approximated GELU (beyond the reference's zoo)."""
    name = "gelu"


def act_name(act) -> str:
    if act is None:
        return ""
    if isinstance(act, str):
        return act
    return act.name
