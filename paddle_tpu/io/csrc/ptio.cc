// ptio — native data-loader runtime for paddle_tpu.
//
// TPU-native equivalent of the reference's C++ data-provider machinery
// (ref: paddle/gserver/dataproviders/DataProvider.h DoubleBuffer:260,
// PyDataProvider2.cpp loadThread_ + memory pool :360-467,
// ProtoDataProvider.cpp binary shards, paddle/utils/Queue.h,
// paddle/utils/Thread.h): a background producer thread reads binary shard
// files, maintains a streaming shuffle pool (the min_pool_size semantics of
// PyDataProvider2), assembles padded dense batches entirely outside the
// Python GIL, and hands them to the consumer through a bounded blocking
// queue (the DoubleBuffer analog) so host IO overlaps device compute.
//
// Shard format "PTSH" v1 (written by paddle_tpu/io/shards.py):
//   char[4] "PTSH"; u32 version; u32 nslots;
//   per slot: u32 kind (0 dense, 1 index, 2 dense_seq, 3 index_seq); u32 dim
//   records until EOF, each record = per-slot payload:
//     dense:      dim * f32
//     index:      i32
//     dense_seq:  u32 len; len * dim * f32
//     index_seq:  u32 len; len * i32
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread ptio.cc -o libptio.so

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

enum SlotKind : uint32_t {
  kDense = 0,
  kIndex = 1,
  kDenseSeq = 2,
  kIndexSeq = 3,
};

struct SlotDesc {
  uint32_t kind = 0;
  uint32_t dim = 0;
};

// One record: raw per-slot payloads (already parsed lengths).
struct Record {
  // per slot: floats or ints + length (1 for non-seq)
  std::vector<std::vector<float>> f;
  std::vector<std::vector<int32_t>> i;
  std::vector<int32_t> len;
};

// One assembled batch, ownership transferred to the consumer side handle.
struct Batch {
  int32_t batch_size = 0;       // 0 => end-of-pass marker
  // per slot: data buffer (float32 or int32), per-row lengths, padded maxlen
  std::vector<std::vector<float>> fdata;
  std::vector<std::vector<int32_t>> idata;
  std::vector<std::vector<int32_t>> lens;
  std::vector<int32_t> maxlen;
};

struct Loader {
  std::vector<std::string> files;
  std::vector<SlotDesc> slots;
  int batch_size = 1;
  int pool_size = 1024;          // shuffle pool target fill
  bool shuffle = true;
  int queue_depth = 4;
  int pad_multiple = 8;
  int repeat = 1;                // 0 = one pass then stop
  uint64_t seed = 0;

  std::thread producer;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::deque<std::unique_ptr<Batch>> queue;
  std::atomic<bool> stop{false};
  std::atomic<bool> done{false};  // producer exited
  std::string error;

  std::unique_ptr<Batch> current;  // last batch handed to the consumer

  ~Loader() {
    stop.store(true);
    cv_push.notify_all();
    cv_pop.notify_all();
    if (producer.joinable()) producer.join();
  }
};

bool read_exact(FILE* fp, void* out, size_t n) {
  return fread(out, 1, n, fp) == n;
}

bool read_header(FILE* fp, std::vector<SlotDesc>* slots, std::string* err) {
  char magic[4];
  uint32_t version = 0, nslots = 0;
  if (!read_exact(fp, magic, 4) || memcmp(magic, "PTSH", 4) != 0) {
    *err = "bad shard magic";
    return false;
  }
  if (!read_exact(fp, &version, 4) || version != 1) {
    *err = "unsupported shard version";
    return false;
  }
  if (!read_exact(fp, &nslots, 4) || nslots == 0 || nslots > 1024) {
    *err = "bad slot count";
    return false;
  }
  slots->resize(nslots);
  for (auto& s : *slots) {
    if (!read_exact(fp, &s.kind, 4) || !read_exact(fp, &s.dim, 4) ||
        s.kind > kIndexSeq) {
      *err = "bad slot descriptor";
      return false;
    }
  }
  return true;
}

// Read one record; returns false on clean EOF, sets err on corruption.
bool read_record(FILE* fp, const std::vector<SlotDesc>& slots, Record* rec,
                 std::string* err) {
  rec->f.assign(slots.size(), {});
  rec->i.assign(slots.size(), {});
  rec->len.assign(slots.size(), 1);
  for (size_t s = 0; s < slots.size(); s++) {
    const auto& d = slots[s];
    uint32_t len = 1;
    if (d.kind == kDenseSeq || d.kind == kIndexSeq) {
      size_t got = fread(&len, 1, 4, fp);
      if (got == 0 && s == 0) return false;  // clean EOF at record boundary
      if (got != 4 || len > (1u << 24)) {
        *err = "corrupt shard (bad seq length)";
        return false;
      }
    }
    rec->len[s] = static_cast<int32_t>(len);
    if (d.kind == kDense || d.kind == kDenseSeq) {
      size_t n = static_cast<size_t>(len) * d.dim;
      rec->f[s].resize(n);
      size_t got = fread(rec->f[s].data(), 4, n, fp);
      if (got == 0 && s == 0 && d.kind == kDense) return false;  // EOF
      if (got != n) {
        *err = "corrupt shard (short dense payload)";
        return false;
      }
    } else {
      size_t n = (d.kind == kIndex) ? 1 : len;
      rec->i[s].resize(n);
      size_t got = fread(rec->i[s].data(), 4, n, fp);
      if (got == 0 && s == 0 && d.kind == kIndex) return false;  // EOF
      if (got != n) {
        *err = "corrupt shard (short index payload)";
        return false;
      }
    }
  }
  return true;
}

int32_t round_up(int32_t n, int32_t m) {
  return m <= 1 ? n : ((n + m - 1) / m) * m;
}

std::unique_ptr<Batch> assemble(const std::vector<SlotDesc>& slots,
                                std::vector<Record>&& recs, int pad_multiple) {
  auto b = std::make_unique<Batch>();
  const int32_t B = static_cast<int32_t>(recs.size());
  b->batch_size = B;
  b->fdata.resize(slots.size());
  b->idata.resize(slots.size());
  b->lens.resize(slots.size());
  b->maxlen.assign(slots.size(), 1);
  for (size_t s = 0; s < slots.size(); s++) {
    const auto& d = slots[s];
    bool is_seq = d.kind == kDenseSeq || d.kind == kIndexSeq;
    int32_t maxlen = 1;
    if (is_seq) {
      for (auto& r : recs) maxlen = std::max(maxlen, r.len[s]);
      maxlen = round_up(maxlen, pad_multiple);
    }
    b->maxlen[s] = maxlen;
    if (is_seq) {
      b->lens[s].resize(B);
      for (int32_t r = 0; r < B; r++) b->lens[s][r] = recs[r].len[s];
    }
    if (d.kind == kDense || d.kind == kDenseSeq) {
      b->fdata[s].assign(static_cast<size_t>(B) * maxlen * d.dim, 0.0f);
      for (int32_t r = 0; r < B; r++) {
        memcpy(b->fdata[s].data() + static_cast<size_t>(r) * maxlen * d.dim,
               recs[r].f[s].data(), recs[r].f[s].size() * 4);
      }
    } else {
      b->idata[s].assign(static_cast<size_t>(B) * maxlen, 0);
      for (int32_t r = 0; r < B; r++) {
        memcpy(b->idata[s].data() + static_cast<size_t>(r) * maxlen,
               recs[r].i[s].data(), recs[r].i[s].size() * 4);
      }
    }
  }
  return b;
}

void push_batch(Loader* L, std::unique_ptr<Batch> b) {
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_push.wait(lk, [&] {
    return L->stop.load() || static_cast<int>(L->queue.size()) < L->queue_depth;
  });
  if (L->stop.load()) return;
  L->queue.push_back(std::move(b));
  L->cv_pop.notify_one();
}

void producer_main(Loader* L) {
  std::mt19937_64 rng(L->seed);
  std::vector<Record> pool;
  pool.reserve(L->pool_size + L->batch_size);

  auto emit_from_pool = [&](bool flush) {
    // Pop batch_size records once the pool is warm (pool_size extra records
    // stay resident for shuffling quality — the min_pool_size semantics).
    int warm = (L->shuffle ? L->pool_size : 0) + L->batch_size;
    while (static_cast<int>(pool.size()) >= (flush ? 1 : warm)) {
      int32_t n = std::min<int32_t>(L->batch_size,
                                    static_cast<int32_t>(pool.size()));
      std::vector<Record> recs;
      recs.reserve(n);
      if (L->shuffle) {
        for (int32_t k = 0; k < n; k++) {
          size_t j = rng() % pool.size();
          recs.push_back(std::move(pool[j]));
          pool[j] = std::move(pool.back());
          pool.pop_back();
        }
      } else {
        // preserve file order
        recs.assign(std::make_move_iterator(pool.begin()),
                    std::make_move_iterator(pool.begin() + n));
        pool.erase(pool.begin(), pool.begin() + n);
      }
      push_batch(L, assemble(L->slots, std::move(recs), L->pad_multiple));
      if (L->stop.load()) return;
    }
  };

  for (int pass = 0; !L->stop.load(); pass++) {
    std::vector<std::string> order = L->files;
    if (L->shuffle) {
      std::shuffle(order.begin(), order.end(), rng);
    }
    for (const auto& path : order) {
      FILE* fp = fopen(path.c_str(), "rb");
      if (!fp) {
        std::lock_guard<std::mutex> lk(L->mu);
        L->error = "cannot open shard: " + path;
        L->done.store(true);
        L->cv_pop.notify_all();
        return;
      }
      std::vector<SlotDesc> slots;
      std::string err;
      if (!read_header(fp, &slots, &err) || slots.size() != L->slots.size()) {
        fclose(fp);
        std::lock_guard<std::mutex> lk(L->mu);
        L->error = err.empty() ? ("shard schema mismatch: " + path)
                               : (err + ": " + path);
        L->done.store(true);
        L->cv_pop.notify_all();
        return;
      }
      Record rec;
      while (!L->stop.load()) {
        err.clear();
        if (!read_record(fp, L->slots, &rec, &err)) {
          if (!err.empty()) {
            fclose(fp);
            std::lock_guard<std::mutex> lk(L->mu);
            L->error = err + ": " + path;
            L->done.store(true);
            L->cv_pop.notify_all();
            return;
          }
          break;  // clean EOF
        }
        pool.push_back(std::move(rec));
        emit_from_pool(false);
      }
      fclose(fp);
      if (L->stop.load()) break;
    }
    emit_from_pool(true);  // drain the pool at pass end
    // end-of-pass marker
    auto eos = std::make_unique<Batch>();
    push_batch(L, std::move(eos));
    if (!L->repeat) break;
  }
  L->done.store(true);
  L->cv_pop.notify_all();
}

}  // namespace

extern "C" {

void* ptio_open(const char** files, int nfiles, int batch_size, int pool_size,
                int shuffle, uint64_t seed, int queue_depth, int pad_multiple,
                int repeat) {
  if (nfiles <= 0 || batch_size <= 0) return nullptr;
  auto L = std::make_unique<Loader>();
  for (int i = 0; i < nfiles; i++) L->files.emplace_back(files[i]);
  L->batch_size = batch_size;
  L->pool_size = std::max(0, pool_size);
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->queue_depth = std::max(1, queue_depth);
  L->pad_multiple = std::max(1, pad_multiple);
  L->repeat = repeat;

  // read the first shard's header for the schema
  FILE* fp = fopen(L->files[0].c_str(), "rb");
  if (!fp) return nullptr;
  std::string err;
  bool ok = read_header(fp, &L->slots, &err);
  fclose(fp);
  if (!ok) return nullptr;

  Loader* raw = L.release();
  raw->producer = std::thread(producer_main, raw);
  return raw;
}

int ptio_nslots(void* h) {
  return static_cast<int>(static_cast<Loader*>(h)->slots.size());
}

void ptio_slot(void* h, int i, uint32_t* kind, uint32_t* dim) {
  auto* L = static_cast<Loader*>(h);
  *kind = L->slots[i].kind;
  *dim = L->slots[i].dim;
}

// Returns batch_size (>0), 0 for end-of-pass, -2 when the stream is
// exhausted (repeat=0), -1 on error.  Buffers stay valid until the next
// ptio_next / ptio_close call.
long ptio_next(void* h, void** data, int32_t** lens, int32_t* maxlens) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_pop.wait(lk, [&] {
    return !L->queue.empty() || L->done.load() || L->stop.load();
  });
  if (!L->error.empty()) return -1;
  if (L->queue.empty()) return -2;  // producer finished
  L->current = std::move(L->queue.front());
  L->queue.pop_front();
  L->cv_push.notify_one();
  lk.unlock();

  Batch* b = L->current.get();
  if (b->batch_size == 0) return 0;  // end of pass
  for (size_t s = 0; s < L->slots.size(); s++) {
    const auto& d = L->slots[s];
    if (d.kind == kDense || d.kind == kDenseSeq) {
      data[s] = b->fdata[s].data();
    } else {
      data[s] = b->idata[s].data();
    }
    lens[s] = b->lens[s].empty() ? nullptr : b->lens[s].data();
    maxlens[s] = b->maxlen[s];
  }
  return b->batch_size;
}

const char* ptio_error(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> lk(L->mu);
  return L->error.c_str();
}

void ptio_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
