"""ctypes bindings for the native data-loader runtime (io/csrc/ptio.cc).

Builds libptio.so with g++ on first use (cached next to the source, keyed
by source mtime) and exposes `NativeShardLoader`, which yields padded
batches as {layer_name: Argument} dicts — the same contract as
data/feeder.make_batch, but with file IO, shuffling, and batch assembly
running in a C++ background thread outside the GIL (ref equivalents:
PyDataProvider2.cpp loadThread_, DataProvider.h DoubleBuffer).

When no C++ toolchain is available, `available()` is False and callers
fall back to the pure-Python shard reader (io/shards.read_shard).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

from paddle_tpu.data.provider import InputType
from paddle_tpu.io import shards as shard_fmt
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.utils.logger import get_logger

log = get_logger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "ptio.cc")
_LIB = os.path.join(os.path.dirname(__file__), "csrc", "libptio.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _build_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", _LIB + ".tmp"]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(_LIB + ".tmp", _LIB)
            except (subprocess.CalledProcessError, FileNotFoundError,
                    subprocess.TimeoutExpired) as e:
                detail = getattr(e, "stderr", b"") or b""
                log.warning("native loader build failed (%s); using Python "
                            "fallback: %s", e, detail.decode()[:500])
                _build_failed = True
                return None
        lib = ctypes.CDLL(_LIB)
        lib.ptio_open.restype = ctypes.c_void_p
        lib.ptio_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.ptio_nslots.argtypes = [ctypes.c_void_p]
        lib.ptio_nslots.restype = ctypes.c_int
        lib.ptio_slot.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint32),
                                  ctypes.POINTER(ctypes.c_uint32)]
        lib.ptio_next.restype = ctypes.c_long
        lib.ptio_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_int32)]
        lib.ptio_error.argtypes = [ctypes.c_void_p]
        lib.ptio_error.restype = ctypes.c_char_p
        lib.ptio_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _build() is not None


class NativeShardLoader:
    """Batches from PTSH shards via the C++ runtime.

    names/types define the Argument mapping (layer name + InputType per
    slot, in shard slot order).  One `passes()` iteration = one epoch.
    """

    def __init__(self, files: Sequence[str], names: Sequence[str],
                 types: Sequence[InputType], batch_size: int,
                 shuffle: bool = True, pool_size: int = 4096,
                 seed: int = 0, queue_depth: int = 4, pad_multiple: int = 8):
        lib = _build()
        assert lib is not None, "native loader unavailable (no g++?)"
        self._lib = lib
        self.names = list(names)
        self.types = list(types)
        self.files = list(files)
        # validate schema against the shard header
        disk = shard_fmt.shard_types(self.files[0])
        want = [(shard_fmt.slot_code(t), t.dim) for t in self.types]
        assert disk == want, f"shard schema {disk} != provider schema {want}"
        arr = (ctypes.c_char_p * len(self.files))(
            *[f.encode() for f in self.files])
        self._h = lib.ptio_open(arr, len(self.files), batch_size,
                                pool_size, int(shuffle), seed, queue_depth,
                                pad_multiple, 1)
        assert self._h, f"failed to open shards {self.files[:2]}..."
        self._n = lib.ptio_nslots(self._h)
        assert self._n == len(self.types)

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ptio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def one_pass(self) -> Iterator[dict[str, Argument]]:
        """Yield batches until the end-of-pass marker."""
        n = self._n
        data = (ctypes.c_void_p * n)()
        lens = (ctypes.POINTER(ctypes.c_int32) * n)()
        maxlens = (ctypes.c_int32 * n)()
        while True:
            got = self._lib.ptio_next(self._h, data, lens, maxlens)
            if got == 0:
                return  # end of pass
            if got == -2:
                return  # stream exhausted
            if got < 0:
                raise RuntimeError(
                    f"native loader: {self._lib.ptio_error(self._h).decode()}")
            B = int(got)
            out: dict[str, Argument] = {}
            for s, (name, t) in enumerate(zip(self.names, self.types)):
                code = shard_fmt.slot_code(t)
                T = int(maxlens[s])
                if code == shard_fmt.DENSE:
                    buf = np.ctypeslib.as_array(
                        ctypes.cast(data[s], ctypes.POINTER(ctypes.c_float)),
                        (B, t.dim))
                    out[name] = Argument(value=buf.copy())
                elif code == shard_fmt.INDEX:
                    buf = np.ctypeslib.as_array(
                        ctypes.cast(data[s], ctypes.POINTER(ctypes.c_int32)),
                        (B,))
                    out[name] = Argument(ids=buf.copy())
                elif code == shard_fmt.DENSE_SEQ:
                    buf = np.ctypeslib.as_array(
                        ctypes.cast(data[s], ctypes.POINTER(ctypes.c_float)),
                        (B, T, t.dim))
                    ln = np.ctypeslib.as_array(lens[s], (B,))
                    out[name] = Argument(value=buf.copy(), lengths=ln.copy())
                else:
                    buf = np.ctypeslib.as_array(
                        ctypes.cast(data[s], ctypes.POINTER(ctypes.c_int32)),
                        (B, T))
                    ln = np.ctypeslib.as_array(lens[s], (B,))
                    out[name] = Argument(ids=buf.copy(), lengths=ln.copy())
            yield out
