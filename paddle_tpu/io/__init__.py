"""Native data IO runtime: PTSH binary shards + C++ background loader.

(ref equivalents: paddle/gserver/dataproviders/{ProtoDataProvider,
PyDataProvider2}.cpp, paddle/utils/{Queue,Thread}.h — see io/csrc/ptio.cc.)
"""

from paddle_tpu.io.shards import (  # noqa: F401
    ShardWriter, read_shard, shard_types, write_shards,
    write_shards_from_provider,
)
from paddle_tpu.io.native import NativeShardLoader, available  # noqa: F401
