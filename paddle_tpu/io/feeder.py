"""ShardFeeder — DataFeeder-compatible batching from PTSH shards.

Drop-in for data/feeder.DataFeeder when the data source is binary shards:
uses the native C++ loader (io/native.py) when a toolchain is present —
shuffle + padding + prefetch all happen off-GIL — and falls back to the
pure-Python shard reader + make_batch otherwise.
"""

from __future__ import annotations

import glob as globmod
import os
import random
from typing import Iterator, Optional, Sequence

from paddle_tpu.data.feeder import make_batch
from paddle_tpu.data.provider import (
    InputType, dense_vector, dense_vector_sequence, integer_value,
    integer_value_sequence,
)
from paddle_tpu.io import native, shards
from paddle_tpu.parameter.argument import Argument

_CODE_TO_TYPE = {
    shards.DENSE: dense_vector,
    shards.INDEX: integer_value,
    shards.DENSE_SEQ: dense_vector_sequence,
    shards.INDEX_SEQ: integer_value_sequence,
}


def expand_files(spec: str) -> list[str]:
    """A shard spec is a file-list file, a glob, or a directory."""
    if os.path.isdir(spec):
        return sorted(globmod.glob(os.path.join(spec, "*.ptsh")))
    if os.path.isfile(spec) and not spec.endswith(".ptsh"):
        with open(spec) as f:
            return [ln.strip() for ln in f if ln.strip()]
    hits = sorted(globmod.glob(spec))
    return hits if hits else [spec]


class ShardFeeder:
    """Same batches()/prefetched_batches() contract as DataFeeder."""

    def __init__(self, files_spec: str, input_names: Sequence[str],
                 batch_size: int, shuffle: bool = True, seed: int = 1,
                 drop_last: bool = True, pool_size: int = 4096,
                 names: Optional[Sequence[str]] = None):
        self.files = expand_files(files_spec)
        assert self.files, f"no shard files match {files_spec!r}"
        disk = shards.shard_types(self.files[0])
        self.types: list[InputType] = [_CODE_TO_TYPE[k](d) for k, d in disk]
        self.names = list(names) if names else list(input_names)
        assert len(self.names) == len(self.types), (
            f"{len(self.types)} shard slots but {len(self.names)} input names "
            f"({self.names}); pass names= to match shard slot order")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.pool_size = pool_size
        self._loader: Optional[native.NativeShardLoader] = None

    def batches(self) -> Iterator[dict[str, Argument]]:
        if native.available():
            if self._loader is None:
                self._loader = native.NativeShardLoader(
                    self.files, self.names, self.types, self.batch_size,
                    shuffle=self.shuffle, pool_size=self.pool_size,
                    seed=self.seed)
            for batch in self._loader.one_pass():
                b = next(iter(batch.values()))
                n = (b.value if b.value is not None else b.ids).shape[0]
                if n < self.batch_size and self.drop_last:
                    continue
                yield batch
            return
        # Python fallback: read + shuffle + pad in-process
        samples = [s for p in self.files for s in shards.read_shard(p)]
        if self.shuffle:
            random.Random(self.seed).shuffle(samples)
        for i in range(0, len(samples), self.batch_size):
            chunk = samples[i:i + self.batch_size]
            if len(chunk) < self.batch_size and self.drop_last:
                continue
            yield make_batch(chunk, self.types, self.names)

    # the native loader already prefetches in its C++ thread
    prefetched_batches = batches

    def device_batches(self, place_fn, timer=None) -> Iterator[dict]:
        """Batches staged onto device one ahead of the consumer: the native
        loader's C++ thread overlaps batch ASSEMBLY; this adds the H2D
        staging overlap on top (same contract as DataFeeder.device_batches)."""
        from paddle_tpu.data.feeder import DeviceDoubleBuffer
        return iter(DeviceDoubleBuffer(self.batches(), place_fn, timer=timer))

    def close(self) -> None:
        if self._loader is not None:
            self._loader.close()
            self._loader = None
