"""PTSH binary shard format — writer and pure-Python reader.

The on-disk format consumed by the native loader (io/csrc/ptio.cc); the
TPU-native analog of the reference's binary proto data shards
(ref: paddle/gserver/dataproviders/ProtoDataProvider.cpp, proto/DataFormat
.proto.m4).  The writer converts any @provider sample stream into shards
once, after which training reads them GIL-free through the C++ runtime.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, Sequence

import numpy as np

from paddle_tpu.data.provider import DataProviderWrapper, InputType, SeqType, SlotKind

MAGIC = b"PTSH"
VERSION = 1

# slot kind codes shared with ptio.cc
DENSE, INDEX, DENSE_SEQ, INDEX_SEQ = 0, 1, 2, 3


def slot_code(t: InputType) -> int:
    if t.seq_type == SeqType.NO_SEQUENCE:
        if t.kind == SlotKind.DENSE:
            return DENSE
        if t.kind == SlotKind.INDEX:
            return INDEX
    else:
        if t.kind == SlotKind.DENSE:
            return DENSE_SEQ
        if t.kind == SlotKind.INDEX:
            return INDEX_SEQ
    raise ValueError(
        f"shard format v1 supports dense/index slots (got {t.kind}/{t.seq_type}); "
        "densify sparse slots or keep them on the Python provider path")


class ShardWriter:
    """Stream records into one shard file."""

    def __init__(self, path: str, types: Sequence[InputType]):
        self.types = list(types)
        self.codes = [slot_code(t) for t in self.types]
        self.fp = open(path, "wb")
        self.fp.write(MAGIC)
        self.fp.write(struct.pack("<II", VERSION, len(self.types)))
        for code, t in zip(self.codes, self.types):
            self.fp.write(struct.pack("<II", code, t.dim))
        self.n = 0

    def write(self, sample: Sequence) -> None:
        assert len(sample) == len(self.types), "slot count mismatch"
        for val, code, t in zip(sample, self.codes, self.types):
            if code == DENSE:
                arr = np.asarray(val, np.float32).reshape(t.dim)
                self.fp.write(arr.tobytes())
            elif code == INDEX:
                self.fp.write(struct.pack("<i", int(val)))
            elif code == DENSE_SEQ:
                arr = np.asarray(val, np.float32).reshape(-1, t.dim)
                self.fp.write(struct.pack("<I", arr.shape[0]))
                self.fp.write(arr.tobytes())
            else:  # INDEX_SEQ
                arr = np.asarray(val, np.int32).reshape(-1)
                self.fp.write(struct.pack("<I", arr.shape[0]))
                self.fp.write(arr.tobytes())
        self.n += 1

    def close(self) -> None:
        self.fp.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_shards(samples: Iterable[Sequence], types: Sequence[InputType],
                 out_dir: str, prefix: str = "data",
                 shard_size: int = 65536) -> list[str]:
    """Split a sample stream into shard files of <= shard_size records."""
    os.makedirs(out_dir, exist_ok=True)
    paths: list[str] = []
    writer = None
    for sample in samples:
        if writer is None or writer.n >= shard_size:
            if writer is not None:
                writer.close()
            path = os.path.join(out_dir, f"{prefix}-{len(paths):05d}.ptsh")
            paths.append(path)
            writer = ShardWriter(path, types)
        writer.write(sample)
    if writer is not None:
        writer.close()
    return paths


def write_shards_from_provider(provider: DataProviderWrapper,
                               files: list[str], out_dir: str,
                               shard_size: int = 65536) -> list[str]:
    """Materialize a @provider's samples as shards (offline conversion —
    the analog of the reference's cache-to-disk provider option)."""
    return write_shards(provider.samples(files), provider.input_types,
                        out_dir, shard_size=shard_size)


def read_shard(path: str) -> Iterator[tuple]:
    """Pure-Python shard reader — fallback oracle for the native loader."""
    with open(path, "rb") as fp:
        assert fp.read(4) == MAGIC, f"bad shard magic in {path}"
        version, nslots = struct.unpack("<II", fp.read(8))
        assert version == VERSION
        slots = [struct.unpack("<II", fp.read(8)) for _ in range(nslots)]
        while True:
            head = fp.read(4)
            if not head:
                return
            sample = []
            for s, (code, dim) in enumerate(slots):
                if s > 0:
                    head = fp.read(4)
                if code == DENSE:
                    buf = head + fp.read(dim * 4 - 4)
                    sample.append(np.frombuffer(buf, np.float32).copy())
                elif code == INDEX:
                    sample.append(struct.unpack("<i", head)[0])
                elif code == DENSE_SEQ:
                    (length,) = struct.unpack("<I", head)
                    buf = fp.read(length * dim * 4)
                    sample.append(
                        np.frombuffer(buf, np.float32).reshape(length, dim).copy())
                else:
                    (length,) = struct.unpack("<I", head)
                    buf = fp.read(length * 4)
                    sample.append(np.frombuffer(buf, np.int32).copy())
            yield tuple(sample)


def shard_types(path: str) -> list[tuple[int, int]]:
    """Read just the (kind, dim) schema of a shard file."""
    with open(path, "rb") as fp:
        assert fp.read(4) == MAGIC, f"bad shard magic in {path}"
        version, nslots = struct.unpack("<II", fp.read(8))
        assert version == VERSION
        return [struct.unpack("<II", fp.read(8)) for _ in range(nslots)]
