"""`python -m paddle_tpu <command>` — the unified CLI entry point.

TPU-native analog of the reference's `paddle` shell wrapper (ref:
paddle/scripts/submit_local.sh.in:109-134: train / merge_model / pserver /
dump_config / make_diagram / version dispatch).  `pserver` is gone — the
fleet collapsed into jax.distributed + XLA collectives; `cluster_launch`
takes its place for starting a multi-host run.
"""

from __future__ import annotations

import sys

COMMANDS = {
    "train": ("paddle_tpu.trainer_main",
              "train/test/checkgrad/time a config (paddle_trainer analog)"),
    "merge_model": ("paddle_tpu.tools.merge_model",
                    "bundle config + weights into one deployable file"),
    "dump_config": ("paddle_tpu.tools.dump_config",
                    "print a parsed config as JSON"),
    "make_diagram": ("paddle_tpu.tools.make_model_diagram",
                     "render the layer graph as graphviz"),
    "show_model": ("paddle_tpu.tools.show_model",
                   "summarize a checkpoint's parameters"),
    "plotcurve": ("paddle_tpu.tools.plotcurve",
                  "plot training-log cost curves"),
    "cluster_launch": ("paddle_tpu.tools.cluster_launch",
                       "start a multi-host run over ssh (pserver-fleet analog)"),
}


def _version() -> str:
    import jax

    from paddle_tpu import __version__
    return (f"paddle_tpu {__version__} (PaddlePaddle v0.9.0 capability "
            f"rebuild, TPU-native) on jax {jax.__version__}")


def usage() -> str:
    lines = ["usage: python -m paddle_tpu <command> [args...]", "",
             "commands:"]
    for name, (_, desc) in COMMANDS.items():
        lines.append(f"  {name:<15} {desc}")
    lines += ["  version         print version", "  --help          this text"]
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("--help", "-h", "help"):
        print(usage())
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "version":
        print(_version())
        return 0
    if cmd not in COMMANDS:
        print(f"unknown command {cmd!r}\n\n{usage()}", file=sys.stderr)
        return 2
    import importlib
    mod = importlib.import_module(COMMANDS[cmd][0])
    rc = mod.main(rest)
    return int(rc or 0)


if __name__ == "__main__":
    sys.exit(main())
