"""@provider decorator and input-type descriptors.

Behavior-compatible analog of the reference's PyDataProvider2
(ref: python/paddle/trainer/PyDataProvider2.py: @provider:206, input types
:57-107 dense_vector/sparse_binary_vector/sparse_vector/integer_value ×
{scalar, sequence}; C++ host gserver/dataproviders/PyDataProvider2.cpp).

A provider is a generator function decorated with @provider(input_types=...);
it yields one sample per iteration, each sample a list/tuple aligned with
input_types.  The TPU DataFeeder (feeder.py) pools samples, shuffles, buckets
sequences by length and emits padded device batches — replacing the reference's
background loadThread + memory-pool machinery.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class SlotKind(enum.Enum):
    DENSE = 0
    SPARSE_BINARY = 1
    SPARSE_VALUE = 2
    INDEX = 3


class SeqType(enum.Enum):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


@dataclass
class InputType:
    """(ref: PyDataProvider2.py InputType)."""

    dim: int
    kind: SlotKind
    seq_type: SeqType = SeqType.NO_SEQUENCE


def dense_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE)


def sparse_binary_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY)


def sparse_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_VALUE)


def integer_value(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX)


def dense_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqType.SEQUENCE)


def sparse_binary_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqType.SEQUENCE)


def sparse_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_VALUE, SeqType.SEQUENCE)


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqType.SEQUENCE)


def integer_value_sub_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqType.SUB_SEQUENCE)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqType.SUB_SEQUENCE)


def sparse_binary_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqType.SUB_SEQUENCE)


def sparse_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_VALUE, SeqType.SUB_SEQUENCE)


class CacheType(enum.Enum):
    """(ref: PyDataProvider2.py CacheType)."""

    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


@dataclass
class ProviderSettings:
    """Passed as first argument to the wrapped generator
    (ref: PyDataProvider2 settings object)."""

    input_types: list[InputType] = field(default_factory=list)
    slots: Optional[dict[str, InputType]] = None   # name -> type when dict given
    should_shuffle: bool = True
    pool_size: int = -1
    cache: CacheType = CacheType.NO_CACHE
    calc_batch_size: Optional[Callable] = None
    args: Any = None
    # user extension point
    logger: Any = None


class DataProviderWrapper:
    """The object produced by @provider; callable like the original function
    but also carries the settings needed by DataFeeder."""

    def __init__(self, fn: Callable, settings: ProviderSettings, init_hook: Optional[Callable]):
        self.fn = fn
        self.settings = settings
        self.init_hook = init_hook
        self.__name__ = getattr(fn, "__name__", "provider")

    def initialize(self, file_list: list[str], **kwargs) -> None:
        if self.init_hook is not None:
            self.init_hook(self.settings, file_list=file_list, **kwargs)

    def samples(self, file_list: list[str]):
        """Iterate all samples of one pass."""
        for f in file_list:
            yield from self.fn(self.settings, f)

    @property
    def input_types(self) -> list[InputType]:
        st = self.settings
        if st.slots is not None:
            return list(st.slots.values())
        return list(st.input_types)

    @property
    def input_names(self) -> Optional[list[str]]:
        if self.settings.slots is not None:
            return list(self.settings.slots.keys())
        return None


class MultiProviderWrapper:
    """Mixes several sub-providers into one sample stream by data ratio
    (ref: gserver/dataproviders/MultiDataProvider.{h,cpp}: each batch draws
    size*ratio_i/total samples from sub-provider i; in test mode every
    sub-provider contributes all of its data).

    All sub-providers must declare the same slot schema.  Presents the
    DataProviderWrapper interface so DataFeeder needs no special casing.
    """

    def __init__(self, subs: list, sub_files: list[list[str]],
                 ratios: Optional[list[int]] = None, is_test: bool = False):
        assert subs, "MultiProviderWrapper needs at least one sub-provider"
        self.subs = subs
        self.sub_files = sub_files
        self.ratios = list(ratios) if ratios else [1] * len(subs)
        assert len(self.ratios) == len(subs)
        self.is_test = is_test
        self.settings = subs[0].settings
        t0 = [type(t).__name__ for t in subs[0].input_types]
        for s in subs[1:]:
            assert [type(t).__name__ for t in s.input_types] == t0, \
                "MultiDataProvider sub-providers must share one slot schema"

    def samples(self, file_list: list[str]):
        """Ratio-weighted round-robin over the sub-provider streams.  The
        TRAIN stream ends when the first sub-provider drains, so the pass's
        overall composition honors the ratios even after the feeder's
        global shuffle (the reference draws size*ratio_i/total per batch —
        same steady-state mixture).  Test mode ignores ratios and
        concatenates everything."""
        if self.is_test:
            for s, files in zip(self.subs, self.sub_files):
                yield from s.samples(files)
            return
        its = [iter(s.samples(files))
               for s, files in zip(self.subs, self.sub_files)]
        while True:
            for i, it in enumerate(its):
                for _ in range(self.ratios[i]):
                    try:
                        yield next(it)
                    except StopIteration:
                        return

    @property
    def input_types(self):
        return self.subs[0].input_types

    @property
    def input_names(self):
        return self.subs[0].input_names


def provider(input_types=None, should_shuffle: bool = True, pool_size: int = -1,
             cache: CacheType = CacheType.NO_CACHE, init_hook: Optional[Callable] = None,
             calc_batch_size: Optional[Callable] = None, **kwargs):
    """(ref: PyDataProvider2.py provider:206)."""

    def deco(fn):
        st = ProviderSettings(should_shuffle=should_shuffle, pool_size=pool_size,
                              cache=cache, calc_batch_size=calc_batch_size)
        if isinstance(input_types, dict):
            st.slots = dict(input_types)
        elif input_types is not None:
            st.input_types = list(input_types)
        return DataProviderWrapper(fn, st, init_hook)

    return deco
