"""DataFeeder — samples -> padded device batches.

Replaces the reference's C++ DataProvider machinery (ref:
paddle/gserver/dataproviders/DataProvider.h DataBatch/DoubleBuffer:260,
PyDataProvider2.cpp loadThread_ + memory pool :360-467): pools samples,
shuffles, buckets sequences by length (so XLA sees few distinct padded
shapes), pads to dense arrays, and prefetches batches on a background thread
(the DoubleBuffer analog).
"""

from __future__ import annotations

import queue
import random
import threading
from typing import Iterator, Optional

import numpy as np

from paddle_tpu.data.provider import DataProviderWrapper, InputType, SeqType, SlotKind
from paddle_tpu.parameter.argument import Argument


def _bucket_len(n: int, bucket_sizes=(8, 16, 32, 48, 64, 96, 128, 192, 256, 384, 512)) -> int:
    for b in bucket_sizes:
        if n <= b:
            return b
    return ((n + 127) // 128) * 128


def _check_sparse_ids(ids: np.ndarray, dim: int, name: str) -> None:
    """Out-of-range feature ids must fail at batch assembly — on device the
    gather would silently clamp to dim-1 and train on the wrong row."""
    hi = int(ids.max()) if ids.size else 0
    lo = int(ids.min()) if ids.size else 0
    if hi >= dim or lo < 0:
        raise ValueError(
            f"sparse slot {name!r}: feature id {hi if hi >= dim else lo} "
            f"out of range for dim={dim}")


def _sparse_row(row, binary: bool):
    """One sparse row -> (ids, vals): a list of column ids for binary slots,
    a list of (id, value) pairs for value slots (ref: PyDataProvider2.py
    sparse_binary_vector vs sparse_vector)."""
    if binary:
        ids = np.asarray(row, np.int32)
        return ids, np.ones(len(row), np.float32)
    ids = np.asarray([p[0] for p in row], np.int32)
    vals = np.asarray([p[1] for p in row], np.float32)
    return ids, vals


def make_batch(samples: list, types: list[InputType], names: list[str],
               pad_len: Optional[int] = None) -> dict[str, Argument]:
    """Assemble one padded batch: sample tuples -> {layer_name: Argument}."""
    B = len(samples)
    out: dict[str, Argument] = {}
    for slot, (name, t) in enumerate(zip(names, types)):
        # samples are tuples aligned with input_types, or dicts keyed by
        # slot name (ref: PyDataProvider2.cpp also accepts dict yields)
        vals = [s[name] if isinstance(s, dict) else s[slot] for s in samples]
        if t.seq_type == SeqType.NO_SEQUENCE:
            if t.kind == SlotKind.DENSE:
                arr = np.asarray(vals, np.float32).reshape(B, t.dim)
                out[name] = Argument(value=arr)
            elif t.kind == SlotKind.INDEX:
                out[name] = Argument(ids=np.asarray(vals, np.int32).reshape(B))
            else:
                # sparse row representation: padded [B, K] nonzero ids +
                # values (1/0 validity for binary slots) — memory ∝ nnz,
                # never ∝ dim (ref: SparseRowMatrix.h; PyDataProvider2
                # sparse_binary_vector / sparse_vector)
                binary = t.kind == SlotKind.SPARSE_BINARY
                K = _bucket_len(max((len(v) for v in vals), default=1) or 1)
                ids = np.zeros((B, K), np.int32)
                w = np.zeros((B, K), np.float32)
                for i, row in enumerate(vals):
                    rid, rv = _sparse_row(row, binary)
                    ids[i, :len(rid)] = rid
                    w[i, :len(rid)] = rv
                _check_sparse_ids(ids, t.dim, name)
                out[name] = Argument(ids=ids, sparse_vals=w, sparse_dim=t.dim)
        elif t.seq_type == SeqType.SUB_SEQUENCE:
            # nested sequence: sample = list of subsequences.  Packed as
            # [B, S, T(, dim)] + lengths [B] (#subsequences) + sub_lengths
            # [B, S] (tokens per subsequence)
            n_sub = np.asarray([len(v) for v in vals], np.int32)
            # bucket the subsequence axis too — exact per-batch maxima would
            # recompile the jitted step for every distinct document shape
            S = _bucket_len(max(int(n_sub.max()) if n_sub.size else 1, 1),
                            bucket_sizes=(2, 4, 8, 16, 32, 64, 128))
            sub_l = np.zeros((B, S), np.int32)
            for i, subs in enumerate(vals):
                for j, ss in enumerate(subs):
                    sub_l[i, j] = len(ss)
            T = pad_len or _bucket_len(max(int(sub_l.max()), 1))
            if t.kind == SlotKind.INDEX:
                arr = np.zeros((B, S, T), np.int32)
                for i, subs in enumerate(vals):
                    for j, ss in enumerate(subs):
                        arr[i, j, :len(ss)] = np.asarray(ss, np.int32)
                out[name] = Argument(ids=arr, lengths=n_sub, sub_lengths=sub_l)
            elif t.kind == SlotKind.DENSE:
                arr = np.zeros((B, S, T, t.dim), np.float32)
                for i, subs in enumerate(vals):
                    for j, ss in enumerate(subs):
                        arr[i, j, :len(ss)] = np.asarray(ss, np.float32)
                out[name] = Argument(value=arr, lengths=n_sub, sub_lengths=sub_l)
            else:
                # sparse rows per timestep of each subsequence: [B, S, T, K]
                # ids + values — the same nnz-proportional representation as
                # the flat-sequence sparse slots, one nesting level deeper
                # (ref: PyDataProvider2.py sparse_*_sub_sequence)
                binary = t.kind == SlotKind.SPARSE_BINARY
                K = _bucket_len(max((len(row) for subs in vals
                                     for ss in subs for row in ss),
                                    default=1) or 1)
                ids = np.zeros((B, S, T, K), np.int32)
                w = np.zeros((B, S, T, K), np.float32)
                for i, subs in enumerate(vals):
                    for j, ss in enumerate(subs):
                        for k, row in enumerate(ss):
                            rid, rv = _sparse_row(row, binary)
                            ids[i, j, k, :len(rid)] = rid
                            w[i, j, k, :len(rid)] = rv
                _check_sparse_ids(ids, t.dim, name)
                out[name] = Argument(ids=ids, sparse_vals=w, sparse_dim=t.dim,
                                     lengths=n_sub, sub_lengths=sub_l)
        else:
            lengths = np.asarray([len(v) for v in vals], np.int32)
            T = pad_len or _bucket_len(int(lengths.max()) if B else 1)
            if t.kind == SlotKind.INDEX:
                arr = np.zeros((B, T), np.int32)
                for i, seq in enumerate(vals):
                    arr[i, :len(seq)] = np.asarray(seq, np.int32)
                out[name] = Argument(ids=arr, lengths=lengths)
            elif t.kind == SlotKind.DENSE:
                arr = np.zeros((B, T, t.dim), np.float32)
                for i, seq in enumerate(vals):
                    arr[i, :len(seq)] = np.asarray(seq, np.float32)
                out[name] = Argument(value=arr, lengths=lengths)
            else:
                # per-timestep sparse rows: [B, T, K] ids + values — same
                # nnz-proportional representation as the non-sequence slots
                # (ref: PyDataProvider2.py sparse_binary_vector_sequence /
                # sparse_vector_sequence)
                binary = t.kind == SlotKind.SPARSE_BINARY
                K = _bucket_len(max((len(row) for seq in vals for row in seq),
                                    default=1) or 1)
                ids = np.zeros((B, T, K), np.int32)
                w = np.zeros((B, T, K), np.float32)
                for i, seq in enumerate(vals):
                    for j, row in enumerate(seq):
                        rid, rv = _sparse_row(row, binary)
                        ids[i, j, :len(rid)] = rid
                        w[i, j, :len(rid)] = rv
                _check_sparse_ids(ids, t.dim, name)
                out[name] = Argument(ids=ids, sparse_vals=w, sparse_dim=t.dim,
                                     lengths=lengths)
    return out


class DeviceDoubleBuffer:
    """Device-resident double buffering: a background thread runs
    `place_fn` (typically stack + `jax.device_put`, or `shard_batch` /
    `stage_stacked_batch` under a mesh) on each item ONE AHEAD of the
    consumer, so host->device staging of batch/k-group i+1 overlaps the
    device computation of i and H2D transfer leaves the dispatch critical
    path (ref: gserver/dataproviders/DataProvider.h DoubleBuffer:260 —
    the reference overlapped batch ASSEMBLY; device staging is the analog
    one level further down).

    `timer`, when given, is a zero-arg callable returning a context
    manager (e.g. ``BarrierTimer.time_h2d``) wrapping each place_fn call,
    which makes the overlap observable in the barrier windows.  `depth`
    bounds how many staged items may be alive ahead of the consumer (the
    thread stages at most depth+1 items beyond the one being consumed).
    Exceptions from the producer or place_fn re-raise in the consumer.

    A consumer that stops iterating early (an exception mid-pass) must
    call `close()` — otherwise the producer thread would sit blocked on
    the bounded queue forever, pinning its staged device buffers; the
    trainer's fused loop closes in a finally block."""

    def __init__(self, items: Iterator, place_fn, timer=None, depth: int = 1):
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self._end = object()
        self._stop = threading.Event()

        def put(item) -> bool:
            """Bounded put that gives up once close() was called; returns
            False when the buffer is shut down."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                for item in items:
                    if self._stop.is_set():
                        return
                    if timer is not None:
                        with timer():
                            staged = place_fn(item)
                    else:
                        staged = place_fn(item)
                    if not put(staged):
                        return
                put(self._end)
            except BaseException as e:   # propagate to the consumer
                put(e)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Release the producer thread and drop staged items (idempotent)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._end:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.close()


class DataFeeder:
    """Batches a provider's samples for one or more passes."""

    def __init__(
        self,
        prov: DataProviderWrapper,
        file_list: list[str],
        input_names: list[str],
        batch_size: int,
        shuffle: Optional[bool] = None,
        seed: int = 1,
        drop_last: bool = True,
        bucket_by_length: bool = True,
        prefetch: int = 2,
        constant_slots: Optional[list] = None,
    ):
        self.prov = prov
        self.file_list = file_list
        names = prov.input_names
        self.names = names if names else input_names
        # constant slots fill the model input names AFTER the provider's
        # slots, each a [B, 1] fixed value (ref: DataProvider.cpp:177-195)
        self.constant_slots = list(constant_slots or [])
        if self.constant_slots:
            if names:          # dict-style provider: names are declared
                extra = [n for n in input_names if n not in names]
            else:              # list-style: provider fills the first slots
                extra = list(input_names[len(self.types):])
            assert len(extra) == len(self.constant_slots), (
                f"constant_slots has {len(self.constant_slots)} value(s) but "
                f"the model leaves {len(extra)} input(s) {extra} unfed by "
                f"the provider's {len(self.types)} slot(s)")
            self._const_names = extra
        else:
            self._const_names = []
        self.types = prov.input_types
        self.batch_size = batch_size
        self.shuffle = prov.settings.should_shuffle if shuffle is None else shuffle
        self.rng = random.Random(seed)
        self.drop_last = drop_last
        self.bucket_by_length = bucket_by_length and any(
            t.seq_type != SeqType.NO_SEQUENCE for t in self.types)
        self.prefetch = prefetch
        self._cache: Optional[list] = None
        self._use_cache = prov.settings.cache.name == "CACHE_PASS_IN_MEM"

    def _all_samples(self) -> list:
        if self._use_cache and self._cache is not None:
            return self._cache
        samples = list(self.prov.samples(self.file_list))
        if self._use_cache:
            self._cache = samples
        return samples

    def _sample_sort_key(self, s) -> int:
        for slot, t in enumerate(self.types):
            if t.seq_type != SeqType.NO_SEQUENCE:
                return len(s[self.names[slot]] if isinstance(s, dict)
                           else s[slot])
        return 0

    def batches(self) -> Iterator[dict[str, Argument]]:
        """One pass of padded batches (host numpy; jit moves them to device)."""
        samples = self._all_samples()
        if self.shuffle:
            samples = list(samples)
            self.rng.shuffle(samples)
        if self.bucket_by_length:
            # length-sorted windows keep batches shape-homogeneous while
            # preserving shuffle at the window level (the reference sorts
            # by length inside SequenceToBatch; here it bounds padding waste)
            window = self.batch_size * 64
            chunks = [samples[i:i + window] for i in range(0, len(samples), window)]
            samples = []
            for ch in chunks:
                ch.sort(key=self._sample_sort_key)
                samples.extend(ch)
        bs = self.batch_size
        calc = self.prov.settings.calc_batch_size
        if calc is not None:
            # cost-weighted batching (ref: PyDataProvider2.py
            # calc_batch_size:265 — each sample contributes a custom batch
            # weight, e.g. its token count; a batch closes when the
            # accumulated weight reaches batch_size, and may exceed it
            # like the reference's can_over_batch_size mode)
            chunks, cur, acc = [], [], 0.0
            for s in samples:
                cur.append(s)
                acc += calc(s)     # raw weight — fractional costs accumulate
                if acc >= bs:
                    chunks.append(cur)
                    cur, acc = [], 0
            if cur and not self.drop_last:
                chunks.append(cur)
        else:
            chunks = [samples[i:i + bs] for i in range(0, len(samples), bs)]
            if chunks and len(chunks[-1]) < bs and self.drop_last:
                chunks.pop()
        if self.shuffle and self.bucket_by_length:
            self.rng.shuffle(chunks)
        for chunk in chunks:
            batch = make_batch(chunk, self.types, self.names)
            for name, val in zip(self._const_names, self.constant_slots):
                batch[name] = Argument(
                    value=np.full((len(chunk), 1), val, np.float32))
            yield batch

    def prefetched_batches(self) -> Iterator[dict[str, Argument]]:
        """Background-thread prefetch (ref: DataProvider.h DoubleBuffer)."""
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        END = object()

        def work():
            try:
                for b in self.batches():
                    q.put(b)
                q.put(END)
            except BaseException as e:  # propagate provider failures to consumer
                q.put(e)

        th = threading.Thread(target=work, daemon=True)
        th.start()
        while True:
            item = q.get()
            if item is END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    def device_batches(self, place_fn, timer=None) -> Iterator:
        """Batches staged onto device one ahead of the consumer (assembly
        prefetch + the H2D DoubleBuffer; see DeviceDoubleBuffer)."""
        return iter(DeviceDoubleBuffer(self.prefetched_batches(), place_fn,
                                       timer=timer))
