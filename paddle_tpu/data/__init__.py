from paddle_tpu.data.provider import (  # noqa: F401
    CacheType,
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    provider,
    sparse_binary_vector,
    sparse_binary_vector_sequence,
    sparse_vector,
)
from paddle_tpu.data.feeder import DataFeeder  # noqa: F401
